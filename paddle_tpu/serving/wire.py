"""Thin line-protocol transport for the serving fleet.

The reference system's whole distributed runtime speaks newline-framed
messages over plain TCP (go/master, the C++ task_master rebuilt in
native/task_master.cc); this module is that discipline for the serving
tier, JSON instead of positional verbs, stdlib sockets only:

* **framing** — one JSON object per ``\\n``-terminated line.
  :func:`send_msg` / :class:`LineConn` cap every read at
  :data:`MAX_LINE` bytes, so a corrupt or malicious peer can burn at
  most one bounded buffer, never the process (:class:`WireError`).
* **per-call timeouts** — every blocking socket op inherits the
  connection's timeout; a peer that stops talking is a
  ``socket.timeout`` (an OSError) after a bounded wait, not a hang.
* **retry with jittered exponential backoff** — :func:`call_once`
  retries transient connect/IO failures the way
  ``MasterClient._retry_delay`` does (uniform jitter over [d/2, d]
  decorrelates a reconnect herd after a router restart).
* **prompt teardown** — :class:`LineServer.close` and
  :meth:`LineConn.close` issue ``shutdown(SHUT_RDWR)`` before
  ``close()``: a peer blocked in ``recv`` unblocks NOW instead of
  waiting out its full read timeout (the MasterServer.stop lesson —
  every fleet-test teardown would otherwise eat the timeout).

Envelope notes (PR 17): a fleet ``generate`` request carries the
router-minted decode ``seed`` (re-fed verbatim on every replay hop so
sampled generations re-drive bit-identically), and each worker ack
carries the member's decode-policy fingerprint ``policy`` — the router
gates replay-journal reuse on it exactly as it gates on the weights
``version``. PR 18 adds the optional ``tenant`` field under the same
discipline: stamped once at the router's front door, re-sent on every
replay hop (the journal lives router-side), absent entirely for
single-tenant traffic so pre-tenant frames stay byte-identical.
PR 20 adds the optional ``model`` field the same way: the generate
envelope names the catalog model the hop must decode under (the
worker activates it or refuses with ``kind="model"``), the ack
carries the member's active model id — the router's third
journal-reuse fence beside ``version`` and ``policy`` — and
``reg``/``hb`` frames from model-named workers carry ``models`` (the
resident set) + ``active_model``; model-less workers send none of
these, so pre-paging frames stay byte-identical.
Control verbs: ``reg``/``hb``/``unreg`` (membership), ``swap``/
``rollback`` (deploys), ``page_in``/``page_out`` (multi-model weight
paging: manifest-verified staged load through the swap gates /
resident-snapshot drop — serving/model_paging.py), ``health``,
``metrics`` (final snapshot ship), and ``stop`` — the
drain-then-exit verb the autoscaler's retire path sends (a
subprocess worker's ``serve_forever`` unblocks, closes, and
unregisters).

Nothing here is constructed by default flags — the module has no
import-time side effects beyond defining classes.
"""

import json
import random
import socket
import threading
import time

__all__ = ["WireError", "MAX_LINE", "send_msg", "encoded_size",
           "LineConn", "LineServer", "call_once", "retry_delay"]

# One framed message may carry a whole replay journal (prompt plus
# every generated token as JSON ints) or a packed feed — 8 MiB bounds
# the read buffer without constraining any realistic request.
MAX_LINE = 8 << 20


class WireError(RuntimeError):
    """Protocol-level failure: over-long line, non-JSON frame, or a
    reply that is not the shape the caller asked for."""


def send_msg(sock, obj):
    """One JSON object as one newline-terminated line (compact
    separators: the token-stream path sends thousands of these)."""
    data = json.dumps(obj, separators=(",", ":")).encode() + b"\n"
    if len(data) > MAX_LINE:
        raise WireError("message of %d bytes exceeds the %d-byte "
                        "frame cap" % (len(data), MAX_LINE))
    sock.sendall(data)


def encoded_size(obj):
    """The exact on-wire byte count ``send_msg`` would frame ``obj``
    as (newline included) — how snapshot shippers budget against
    :data:`MAX_LINE` without paying a throwaway send."""
    return len(json.dumps(obj, separators=(",", ":")).encode()) + 1


def retry_delay(attempt, backoff=0.05, cap=2.0):
    """Jittered exponential backoff (MasterClient discipline): uniform
    over [d/2, d] with d = min(cap, backoff * 2**attempt)."""
    d = min(cap, backoff * (2 ** attempt))
    return d * (0.5 + 0.5 * random.random())


class LineConn:
    """One framed connection: ``send(obj)`` / ``recv() -> obj|None``
    (None = orderly EOF). Not thread-safe; give each thread its own,
    or split send/recv between exactly two threads (socket objects
    support one reader + one writer concurrently, which is how the
    worker streams tokens while watching for a client reset)."""

    def __init__(self, sock, timeout=None):
        if timeout is not None:
            sock.settimeout(timeout)
        self.sock = sock
        self._rfile = sock.makefile("rb")

    @classmethod
    def connect(cls, addr, timeout=10.0):
        return cls(socket.create_connection(tuple(addr),
                                            timeout=timeout),
                   timeout=timeout)

    def settimeout(self, timeout):
        self.sock.settimeout(timeout)

    def send(self, obj):
        send_msg(self.sock, obj)

    def recv(self):
        """Next decoded message, or None on EOF. Raises WireError on
        an over-long or non-JSON line, socket.timeout (OSError) on a
        silent peer."""
        line = self._rfile.readline(MAX_LINE + 1)
        if not line:
            return None
        if len(line) > MAX_LINE:
            raise WireError("peer sent a line past the %d-byte cap"
                            % MAX_LINE)
        try:
            return json.loads(line)
        except ValueError as exc:
            raise WireError("bad frame: %r" % line[:80]) from exc

    def close(self):
        """shutdown(SHUT_RDWR) then close: the peer's blocked recv
        returns immediately instead of waiting out its timeout."""
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        for f in (self._rfile, self.sock):
            try:
                f.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def call_once(addr, obj, timeout=5.0, retries=3, backoff=0.05):
    """One request/reply round trip on a fresh connection, with
    jittered-backoff retries on transient connect/IO failures — the
    control-plane shape (register, heartbeat, swap). Raises
    ConnectionError when every attempt failed, WireError on a framing
    violation (not retried: the peer is speaking, just wrongly)."""
    last = None
    for attempt in range(retries):
        try:
            with LineConn.connect(addr, timeout=timeout) as conn:
                conn.send(obj)
                reply = conn.recv()
            if reply is None:
                raise ConnectionError("peer closed before replying")
            return reply
        except WireError:
            raise
        except (OSError, ConnectionError) as exc:
            last = exc
        if attempt + 1 < retries:
            # back off only when another attempt remains — the final
            # failure raises immediately instead of sleeping dead
            # latency into every failover/rollback/teardown path
            time.sleep(retry_delay(attempt, backoff=backoff))
    raise ConnectionError("no reply from %s:%d after %d attempts: %r"
                          % (tuple(addr) + (retries, last)))


class LineServer:
    """Threaded accept loop: ``handler(conn, msg)`` per received
    message, one daemon thread per connection. ``close()`` shuts the
    listener AND every live connection down (SHUT_RDWR first), so
    peers blocked in recv unblock promptly and the accept thread
    joins bounded."""

    def __init__(self, handler, host="127.0.0.1", port=0,
                 timeout=None, name="line-server"):
        self.handler = handler
        self.timeout = timeout
        self.name = name
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._closed = False
        self._conns = set()
        self._lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=name)
        self._accept_thread.start()

    @property
    def addr(self):
        return (self.host, self.port)

    def _accept_loop(self):
        while not self._closed:
            try:
                sock, _peer = self._sock.accept()
            except OSError:
                return  # listener closed
            conn = LineConn(sock, timeout=self.timeout)
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name="%s-conn" % self.name).start()

    def _serve_conn(self, conn):
        try:
            while True:
                try:
                    msg = conn.recv()
                except (WireError, OSError):
                    return
                if msg is None:
                    return
                try:
                    if self.handler(conn, msg) is False:
                        return  # handler took ownership / closed
                except Exception:
                    # a handler bug must not kill the accept fabric;
                    # best-effort error frame, then drop the conn
                    try:
                        conn.send({"ok": False,
                                   "error": "internal handler error"})
                    except OSError:
                        pass
                    return
        finally:
            conn.close()
            with self._lock:
                self._conns.discard(conn)

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = list(self._conns)
        # a thread blocked in accept() is NOT reliably woken by
        # close() alone on Linux — shutdown first, and kick it with a
        # throwaway self-connect as the portable fallback
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            kick = socket.create_connection((self.host, self.port),
                                            timeout=0.2)
            kick.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        for conn in conns:
            conn.close()  # SHUT_RDWR: blocked peers unblock NOW
        self._accept_thread.join(timeout=2.0)
