"""Serving fleet: routed multi-process inference with membership,
cross-process failover, and rolling deploys.

Everything serving-side so far — engine replicas, the generation
scheduler, breakers, token-replay failover, hot swap — lives inside
one process. This module is the tier above, the seam the reference
system built its whole distributed runtime for (PAPER.md §2): one
process's death must be an event the fleet absorbs, not an outage.

* :class:`FleetRouter` — the front door over N engine *processes*:

  - **membership** on the task-master discipline (PR 6): workers
    REGister and heartbeat over the line protocol; a missed deadline
    drops the member, bumps the fleet *generation*, and fences what
    the dead member still says — a reply landing after its member was
    declared dead is discarded and the request re-driven, never
    trusted (``paddle_fleet_fenced_replies_total``). A genuinely new
    member joining also bumps the generation, so stale world views
    are always fenced into a re-register.
  - **routing**: least-loaded placement over healthy members with a
    per-member :class:`~paddle_tpu.serving.resilience.ReplicaBreaker`
    (PR 5's breaker promoted one tier up: closed -> open on
    consecutive failures or a single hang, cooldown-gated trial
    re-admission), member-labelled gauges, and request-latency
    histograms.
  - **cross-process failover**: the router journals ``prompt ⊕
    tokens-so-far`` per request (workers stream each token back), so
    a killed member's in-flight generations re-drive on a peer by
    re-submitting the journal — exactly the PR-9 replay path, one
    process up: the peer prefills the history and decoding continues
    token-for-token identical to a fault-free run (sampled policies
    included: the router mints the request's decode seed once and
    re-feeds it on every hop). A journal is only reusable on a peer
    serving the SAME weights version AND the same decode-policy
    fingerprint (acked by each member); across either boundary it is
    discarded and the generation restarts from the prompt
    (mixed-version — or mixed-policy — output would be neither
    side's answer).
  - **rolling deploys**: drain one member, ``swap`` it (the worker
    applies the push through the PR-7/PR-9 swap gates), canary-scope
    a fraction of live traffic to it, watch; a watch failure rolls
    the WHOLE fleet back to the prior version and aborts. Clients
    see zero errors either way — canary failures replay onto stable
    members.

* :class:`EngineWorker` — the process wrapper a member runs: serves a
  local :class:`~paddle_tpu.serving.generation.GenerationScheduler`
  (or a stateless :class:`~paddle_tpu.serving.engine.ServingEngine`)
  over the JSON-line wire (``serving/wire.py``: length-capped reads,
  per-call timeouts, jittered retry), registers with the router,
  heartbeats on ``fleet_heartbeat_ms``, streams tokens as they
  decode, and answers ``swap``/``rollback``/``health``. Cold members
  warm through the PR-7 persistent compile cache / AOT artifacts, so
  scale-up-under-load is scale-up-to-first-token.

Cross-process tracing (PR 12, promoted over the wire): the request
envelope carries the router-minted trace id; the router stamps a
``fleetHop`` span per dispatch and a ``memberRecv`` child from the
worker's ack, so one request killed mid-generation reads router ->
dead member -> replay-on-peer in a single ``/debug/trace`` tree.

Multi-tenancy (PR 18): requests carry a **tenant id** end-to-end —
``submit(tenant=...)`` -> the generate envelope -> the worker's
backend (signature-gated, like the seed) — and the journal living
router-side means every replay hop re-sends it for free, exactly the
PR-17 seed discipline. With a ``tenants`` table armed (the
``fleet_tenants`` flag, or the constructor arg) the router enforces
per-tenant **admission quotas** (max in-flight; over-quota submits
raise the typed
:class:`~paddle_tpu.serving.batcher.TenantQuotaError` and charge
``paddle_serving_tenant_shed_total{tenant=...}`` — a bursting tenant
sheds ITS traffic while the others' p99 holds) and **priority
tiers** (placement under contention yields to strictly
higher-priority waiters). Per-tenant latency histograms feed
per-tenant SLOTracker verdicts under ``/debug/slo`` when both the
SLO target and the table are armed.

Autoscaling (serving/autoscale.py): an attached
:class:`~paddle_tpu.serving.autoscale.FleetAutoscaler` rides the
monitor tick — spawns EngineWorker processes on SLO pressure, drains
and retires them idle (``retire_member``), bounded by
``fleet_members_min``/``fleet_members_max``. The router never
constructs one.

Multi-model paging (serving/model_paging.py, PR 20): with a model
catalog armed (the ``fleet_models`` flag / the ``models=`` ctor arg)
weights become a *paged* resource — each member advertises its
resident model set on REG and every heartbeat (generation-fenced
like membership itself), placement gains a residency-affinity term
(a tenant's request routes to a member already holding its model), a
request for a nowhere-resident model demand-pages it onto the
least-loaded member through the worker's swap gates (``page_in``:
manifest-verified staged load -> flip, bounded by
``model_page_timeout_ms`` and charged to the autoscaler's
spawn-failure budget on wedge), LRU eviction pressure holds each
member's resident-set bytes under ``member_resident_bytes`` (never
evicting a model with in-flight requests — the BlockPool refcount
discipline applied to whole weight sets), and the replay journal
gains the model id as its third fence beside weights version and
decode policy: a journal can never splice onto the wrong model, and
a journal whose model was paged out re-pages it on the target member
BEFORE re-drive — a SIGKILL'd member's in-flight generations land
bit-identically on a peer that didn't hold the model when the
request started.

Fault sites (resilience/faults.py): ``fleet_member_kill`` (worker
side, indexed by streamed-token count — ``action="kill"`` SIGKILLs
the worker mid-generation), ``fleet_network_partition`` (router side
before dispatch, indexed by member id — and the worker's heartbeat
loop swallows beats under the same site, so one arm simulates both
directions of a partition), ``fleet_slow_member`` (worker side before
serving, indexed by member id — arm a callback sleeping past the
router's call timeout), plus the autoscaler's ``fleet_spawn_fail`` /
``fleet_spawn_slow`` (serving/autoscale.py) and the paging sites
``model_page_in_fail`` / ``model_page_in_slow`` /
``model_evict_race`` (serving/model_paging.py).

Default flags construct NONE of this: no router, no worker, no
sockets, no threads, no autoscaler, no tenant table, no model
catalog. ``fleet_heartbeat_ms`` / ``fleet_members_min`` /
``fleet_canary_fraction`` / ``fleet_tenants`` / ``fleet_models`` are
read only inside these constructors (and ``member_resident_bytes`` /
``model_page_timeout_ms`` only when a catalog is actually armed) —
single-process serving behavior and hot-path flag-check counts are
byte-identical with the fleet unused.
"""

import inspect
import itertools
import json
import os
import queue
import threading
import time
import weakref
from concurrent.futures import Future

import numpy as np

from .. import config as _config
from ..observability import aggregate as _aggregate
from ..observability import flight as _flight
from ..observability import metrics as _metrics
from ..observability import request_trace as _rtrace
from ..observability import slo as _slo
from ..resilience import faults as _faults
from ..utils import log as _log
from . import model_paging as _paging
from . import resilience as _sres
from . import wire as _wire
from .batcher import _WAIT_ALPHA, TenantQuotaError, _resolve
from .decoding.policy import GREEDY_FINGERPRINT, mint_seed
from .resilience import (ReplicaBreaker, ServingDeadlineError,
                         ServingUnavailableError)

__all__ = ["FleetRouter", "EngineWorker", "TenantQuotaError"]

_REQUESTS = _metrics.REGISTRY.counter(
    "paddle_fleet_requests_total",
    "Generation requests accepted by a fleet router")
_FAILOVERS = _metrics.REGISTRY.counter(
    "paddle_fleet_failover_total",
    "Requests re-driven on a peer member after a member failure "
    "(journal re-submit — the PR-9 replay path, one process up)")
_DEATHS = _metrics.REGISTRY.counter(
    "paddle_fleet_member_deaths_total",
    "Members dropped for a missed heartbeat deadline")
_FENCED = _metrics.REGISTRY.counter(
    "paddle_fleet_fenced_replies_total",
    "Replies discarded because their member had been declared dead "
    "by the time they landed (generation fencing, serving tier)")
_JOURNAL_RESETS = _metrics.REGISTRY.counter(
    "paddle_fleet_journal_resets_total",
    "Replay journals discarded at a fence, by reason (version: the "
    "only willing peer served different weights; policy: a different "
    "decode-policy fingerprint; model: a different model id — the "
    "generation restarts from the prompt; a spliced response is "
    "never served)", labelnames=("reason",))
_DEPLOYS = _metrics.REGISTRY.counter(
    "paddle_fleet_deploys_total",
    "Rolling deploys by outcome", labelnames=("outcome",))
_ROLLBACKS = _metrics.REGISTRY.counter(
    "paddle_fleet_rollbacks_total",
    "Fleet-wide rollbacks (watch failure or swap failure mid-deploy)")
_GENERATION = _metrics.REGISTRY.gauge(
    "paddle_fleet_generation",
    "Fleet membership generation (bumps on every join/death)",
    labelnames=("router",))
_MEMBERS_LIVE = _metrics.REGISTRY.gauge(
    "paddle_fleet_members_live",
    "Members currently in the routing rotation",
    labelnames=("router",))
_MEMBER_INFLIGHT = _metrics.REGISTRY.gauge(
    "paddle_fleet_member_inflight",
    "Requests currently dispatched to the member (least-loaded "
    "placement key)", labelnames=("member",))
_REQUEST_MS = _metrics.REGISTRY.histogram(
    "paddle_fleet_request_ms",
    "Router submit -> resolution per fleet request (all hops)",
    buckets=_metrics.LATENCY_MS_BUCKETS)
_TENANT_REQUEST_MS = _metrics.REGISTRY.histogram(
    "paddle_fleet_tenant_request_ms",
    "Router submit -> resolution, one child per tenant (the "
    "per-tenant slice of paddle_fleet_request_ms — a separate family "
    "because a registered family's labelnames are immutable); only "
    "populated when the router has a tenant table",
    labelnames=("tenant",), buckets=_metrics.LATENCY_MS_BUCKETS)
_TENANT_DEADLINE = _metrics.REGISTRY.counter(
    "paddle_fleet_tenant_deadline_total",
    "Deadline-expired fleet requests attributed to one tenant "
    "(feeds that tenant's SLO bad count)", labelnames=("tenant",))
_TENANT_ACTIVE = _metrics.REGISTRY.gauge(
    "paddle_fleet_tenant_active",
    "Requests one tenant currently holds in flight at the router "
    "(the quantity its admission quota bounds)",
    labelnames=("tenant",))
_RECOVERY_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_fleet_recovery_seconds",
    "Member failure -> first replayed token streaming from a peer "
    "(kill-to-first-replayed-token)")
_WORKER_DONE = _metrics.REGISTRY.counter(
    "paddle_fleet_worker_done_total",
    "Requests this member process completed (the per-member side of "
    "the fleet conservation ledger: aggregated deltas must equal the "
    "router-observed completions)")

_ROUTER_SEQ = itertools.count()
_WORKER_INCARNATION_SEQ = itertools.count()


class _MemberError(RuntimeError):
    """A member failed a request server-side (error frame, mid-stream
    EOF, or a fenced stale reply) — failover material, charged to the
    member's breaker, never surfaced while replay budget remains."""


class _VersionRetry(Exception):
    """The member's ack revealed a weights version the router's cache
    didn't know (out-of-band swap, second router deploying): the
    journal sent with the hop was generated under OTHER weights, so
    the hop is abandoned and the request retried from the prompt.
    Not a member failure — no breaker charge, no replay burned."""


class _ModelRetry(Exception):
    """The member refused the hop because the request's model is not
    resident there (paged out between placement and dispatch, or the
    router's residency view was stale). Not a member failure — no
    breaker charge, no replay burned; the serve loop corrects its
    residency view and re-drives through ``_ensure_resident``, which
    re-pages the model first."""


class _Member:
    __slots__ = ("id", "addr", "state", "joined_gen", "deadline",
                 "version", "policy", "inflight", "served", "failures",
                 "breaker", "conns", "label", "index", "residency",
                 "active_model", "paging")

    def __init__(self, mid, addr, gen, label, index):
        self.id = mid
        self.addr = tuple(addr)
        self.state = "live"   # live | draining | canary | dead
        self.joined_gen = gen
        self.deadline = None  # monotonic heartbeat deadline
        self.version = None   # last weights tag the member reported
        self.policy = None    # last decode-policy fingerprint reported
        self.inflight = 0
        self.served = 0       # completions since the last swap (watch)
        self.failures = 0     # failures since the last swap (watch)
        self.breaker = None
        self.conns = set()    # open per-request data connections
        self.label = label    # "f<router>:<member>" — gauge namespace
        self.index = index    # dense join order (breaker index)
        # multi-model residency (PR 20): what this member advertises
        # as paged in, generation-fenced like membership itself
        self.residency = _paging.ModelResidencySet()
        self.active_model = None  # model id the member last acked
        self.paging = False       # a page-in is in flight on it


class _Tenant:
    """One admission-table row: quota (max in-flight at the router,
    0 = unlimited), priority (lower wins placement under contention),
    and the live accounting the quota check reads."""
    __slots__ = ("id", "quota", "priority", "active", "sheds", "label")

    def __init__(self, tid, quota, priority, label):
        self.id = tid
        self.quota = int(quota or 0)
        self.priority = int(priority or 0)
        self.active = 0
        self.sheds = 0
        self.label = label   # "f<router>:<tenant>" — child namespace


class _FleetRequest:
    __slots__ = ("prompt", "tokens", "max_new", "eos_id", "deadline",
                 "future", "meta", "ctx", "replays", "charged",
                 "failed_on", "canary", "tokens_version",
                 "tokens_policy", "seed", "version",
                 "version_start", "member", "fail_t", "t_submit",
                 "tenant", "tenant_entry", "model", "tokens_model",
                 "model_counted", "model_retries")

    def __init__(self, prompt, max_new, eos_id, deadline, meta,
                 seed=0, tenant=None, model=None):
        self.prompt = [int(t) for t in prompt]
        self.tokens = []          # the replay journal's generated half
        self.max_new = max_new
        self.eos_id = eos_id
        self.deadline = deadline  # absolute monotonic, or None
        self.future = Future()
        self.meta = meta
        self.ctx = None
        self.replays = 0
        self.charged = False      # at-most-one breaker charge (PR 5/9)
        self.failed_on = set()    # member ids this request failed on
        self.canary = None        # pinned canary routing for one hop
        self.tokens_version = None  # weights tag that produced tokens
        self.tokens_policy = None   # decode-policy fp that produced them
        self.seed = int(seed)     # minted ONCE; re-fed on every replay
        self.version = None
        self.version_start = None
        self.member = None
        self.fail_t = None        # failure instant, for recovery hist
        self.t_submit = time.perf_counter()
        # tenant id, carried end-to-end like the seed: submit ->
        # envelope -> (journal lives router-side, so every replay hop
        # re-sends it for free)
        self.tenant = None if tenant is None else str(tenant)
        self.tenant_entry = None  # admission row to release, or None
        # the model this request targets (catalog-armed routers only):
        # carried on every hop's envelope like the seed; the third
        # journal fence beside weights version and decode policy
        self.model = None if model is None else str(model)
        self.tokens_model = None  # model id that produced the journal
        self.model_counted = False  # residency hit/miss counted once
        self.model_retries = 0    # bounded model-residency re-drives

    def journal(self):
        return self.prompt + self.tokens

    def remaining(self):
        if self.max_new is None:
            return None
        return max(0, int(self.max_new) - len(self.tokens))


class FleetRouter:
    """Front-end router over N :class:`EngineWorker` processes.

    Construct it, point workers' ``router_addr`` at :attr:`addr`, and
    ``submit(prompt) -> Future`` routes over whoever is alive. Nothing
    global is touched at defaults — the fleet flags are read here
    and in :class:`EngineWorker` only.

    ``heartbeat_timeout_ms`` (default ``3 x fleet_heartbeat_ms``) is
    the membership deadline; 0 disables reaping (manual membership —
    unit tests drive deaths explicitly). ``breaker_failures`` defaults
    to the ``serving_breaker_failures`` flag (0 = no breakers).
    ``replay_attempts`` bounds cross-process re-drives per request.
    ``canary_fraction`` (default: the ``fleet_canary_fraction`` flag)
    is the share of live traffic a mid-deploy canary member receives;
    ``members_min`` (default: the ``fleet_members_min`` flag) is the
    /healthz liveness threshold and the ``wait_members`` default.
    ``tenants`` (default: the ``fleet_tenants`` flag) arms the
    multi-tenant admission table — ``{tenant: {"quota": N,
    "priority": P}}``, ``"*"`` for the unknown-tenant policy;
    ``member_inflight_limit`` (> 0) caps per-member in-flight so
    placement becomes a contended resource (requests queue at the
    router — what priority tiers and the placement-wait EWMA act on).
    ``models`` (default: the ``fleet_models`` flag) arms the model
    catalog — ``{model id: {"params_path"/"model_dir", "tag",
    "bytes", "tenants"}}`` — and with it residency-affinity routing,
    demand paging, and eviction pressure; ``resident_bytes`` /
    ``page_timeout_ms`` (defaults: the ``member_resident_bytes`` /
    ``model_page_timeout_ms`` flags, read only when a catalog is
    armed) bound a member's resident set and one page-in.
    """

    def __init__(self, host="127.0.0.1", port=0,
                 heartbeat_timeout_ms=None, breaker_failures=None,
                 breaker_cooldown_ms=None, replay_attempts=3,
                 call_timeout=120.0, connect_timeout=5.0,
                 placement_timeout=30.0, canary_fraction=None,
                 members_min=None, metrics_interval_ms=None,
                 slo_target_p99_ms=None, slo_windows=None,
                 tenants=None, member_inflight_limit=0,
                 models=None, resident_bytes=None,
                 page_timeout_ms=None):
        self._rid = next(_ROUTER_SEQ)
        if heartbeat_timeout_ms is None:
            heartbeat_timeout_ms = \
                3.0 * float(_config.get_flag("fleet_heartbeat_ms"))
        self.heartbeat_timeout = float(heartbeat_timeout_ms) / 1e3
        if breaker_failures is None:
            breaker_failures = _config.get_flag(
                "serving_breaker_failures")
        self.breaker_failures = int(breaker_failures or 0)
        if breaker_cooldown_ms is None:
            breaker_cooldown_ms = _config.get_flag(
                "serving_breaker_cooldown_ms")
        self.breaker_cooldown = float(breaker_cooldown_ms) / 1e3
        self.replay_attempts = int(replay_attempts or 0)
        self.call_timeout = float(call_timeout)
        self.connect_timeout = float(connect_timeout)
        self.placement_timeout = float(placement_timeout)
        if canary_fraction is None:
            canary_fraction = _config.get_flag("fleet_canary_fraction")
        self.canary_fraction = float(canary_fraction)
        if members_min is None:
            members_min = _config.get_flag("fleet_members_min")
        self.members_min = int(members_min)
        if tenants is None:
            tenants = _config.get_flag("fleet_tenants")
        # the tenant table: None (default) = single-tenant router, no
        # table, no per-tenant children, submit(tenant=) carried for
        # tracing only. A "*" row is the policy unknown tenants get.
        self._tenants = None
        self._tenant_default = (0, 0)   # (quota, priority) fallback
        self._tenant_slos = {}
        if tenants:
            self._tenants = {}
            for tid, pol in dict(tenants).items():
                if isinstance(pol, dict):
                    quota = pol.get("quota", 0)
                    priority = pol.get("priority", 0)
                else:
                    quota, priority = pol
                if str(tid) == "*":
                    self._tenant_default = (int(quota or 0),
                                            int(priority or 0))
                else:
                    tid = str(tid)
                    self._tenants[tid] = _Tenant(
                        tid, quota, priority,
                        "f%d:%s" % (self._rid, tid))
        # the model catalog: None (default) = single-model fleet —
        # no catalog, no residency routing, no paging verbs, every
        # envelope/heartbeat frame byte-identical. The byte budget
        # and page timeout are read ONLY when a catalog is armed, so
        # default construction reads exactly one extra flag.
        if models is None:
            models = _config.get_flag("fleet_models")
        self._catalog = None
        self._model_slos = {}
        self._paging = {}          # model id -> in-flight page-in Event
        self.resident_bytes = 0
        self.page_timeout = 0.0
        if models:
            self._catalog = _paging.ModelCatalog.from_value(models)
            if resident_bytes is None:
                resident_bytes = _config.get_flag(
                    "member_resident_bytes")
            self.resident_bytes = int(resident_bytes or 0)
            if page_timeout_ms is None:
                page_timeout_ms = _config.get_flag(
                    "model_page_timeout_ms")
            self.page_timeout = float(page_timeout_ms or 0.0) / 1e3
        # per-member in-flight cap: 0 (default) = least-loaded only,
        # members absorb any depth. >0 makes placement a real resource
        # (requests queue AT THE ROUTER when every member is full),
        # which is what gives priority tiers and the placement-wait
        # EWMA something to act on.
        self.member_inflight_limit = int(member_inflight_limit or 0)
        # placement-wait EWMA (the batcher's admission signal, one
        # tier up): an autoscaler reads it as its load-rising input
        self.place_wait_ewma = 0.0
        self._sheds = 0            # router-local sheds (quota refusals)
        self._waiters = {}         # priority -> placement waiters
        self._autoscaler = None    # attached FleetAutoscaler, or None
        if metrics_interval_ms is None:
            metrics_interval_ms = _config.get_flag(
                "fleet_metrics_interval_ms")
        self.metrics_interval = float(metrics_interval_ms or 0.0) / 1e3
        # the aggregator is pure ingest-side state (no threads, no
        # sockets): always constructed, it only grows content when
        # members actually ship snapshots
        self._aggregator = _aggregate.FleetAggregator(
            "f%d" % self._rid, interval_s=self.metrics_interval)
        if slo_target_p99_ms is None:
            slo_target_p99_ms = _config.get_flag("slo_target_p99_ms")
        self.slo = None
        if float(slo_target_p99_ms or 0.0) > 0:
            # the router's SLO view is client-observed: its own
            # submit->resolution histogram plus the shed/deadline
            # counters (NOT the members' server-side latencies)
            self.slo = _slo.SLOTracker(
                label="f%d" % self._rid,
                target_p99_ms=float(slo_target_p99_ms),
                windows=slo_windows,
                source=_slo.local_source(
                    histogram="paddle_fleet_request_ms"))
            if self._tenants:
                # one tracker per NAMED tenant, each reading only its
                # own labeled children — a bursting tenant burns its
                # own budget, the victim's verdict stays green
                for tid, entry in sorted(self._tenants.items()):
                    self._tenant_slos[tid] = _slo.SLOTracker(
                        label=entry.label,
                        target_p99_ms=float(slo_target_p99_ms),
                        windows=slo_windows,
                        source=_slo.labeled_source(
                            histogram="paddle_fleet_tenant_request_ms",
                            bad_counters=(
                                "paddle_serving_tenant_shed_total",
                                "paddle_fleet_tenant_deadline_total"),
                            label="tenant", value=entry.label))
            if self._catalog is not None:
                # one tracker per catalog model (same discipline as
                # the per-tenant slice): /debug/slo answers "which
                # MODEL's p99 is blown" — a paged-out model's churn
                # burns its own budget, its co-resident's stays green
                for model_id in self._catalog.ids():
                    mlabel = "f%d:%s" % (self._rid, model_id)
                    self._model_slos[model_id] = _slo.SLOTracker(
                        label=mlabel,
                        target_p99_ms=float(slo_target_p99_ms),
                        windows=slo_windows,
                        source=_slo.labeled_source(
                            histogram="paddle_fleet_model_request_ms",
                            bad_counters=(
                                "paddle_fleet_model_deadline_total",),
                            label="model", value=mlabel))
        self._members = {}          # member id -> _Member
        self._generation = 0
        self._member_seq = itertools.count()
        self._lock = threading.Lock()
        self._closed = False
        self._canary = None         # member id mid-canary, or None
        self._canary_tick = 0
        self._deploy_lock = threading.Lock()
        self._gauge("generation").set(0)
        self._gauge("live").set(0)
        self._server = _wire.LineServer(
            self._control, host=host, port=port,
            timeout=30.0, name="fleet-router-%d" % self._rid)
        self._monitor_stop = threading.Event()
        self._monitor = None
        if self.heartbeat_timeout > 0:
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name="fleet-monitor-%d" % self._rid)
            self._monitor.start()
        from ..observability import health as _health
        self._health_name = "fleet%d" % self._rid
        _health.register_health(self._health_name,
                                _router_health(weakref.ref(self)))
        # introspection surfaces (weakref-closed, like health): the
        # merged /metrics view, the /debug/fleet document, the
        # /debug/slo verdict, and the flight-recorder context so a
        # breaker-open bundle carries the fleet state that triggered it
        ref = weakref.ref(self)
        _health.register_provider("metrics", self._health_name,
                                  _router_metrics(ref))
        _health.register_provider("fleet", self._health_name,
                                  _router_fleet(ref))
        if self.slo is not None:
            _health.register_provider("slo", self._health_name,
                                      _router_slo(ref))
        _flight.RECORDER.add_context(self._health_name,
                                     _router_flight_context(ref))

    # -- plumbing ---------------------------------------------------------
    @property
    def addr(self):
        return self._server.addr

    @property
    def generation(self):
        return self._generation

    @property
    def label(self):
        """The router's metric-namespace label ("f<rid>")."""
        return "f%d" % self._rid

    def _gauge(self, which):
        label = "f%d" % self._rid
        fam = _GENERATION if which == "generation" else _MEMBERS_LIVE
        return fam.labels(router=label)

    def _label(self, mid):
        return "f%d:%s" % (self._rid, mid)

    def _live_locked(self):
        return [m for m in self._members.values()
                if m.state in ("live", "draining", "canary")]

    def members_live(self):
        with self._lock:
            return sorted(m.id for m in self._live_locked())

    def member_versions(self):
        with self._lock:
            return {m.id: m.version for m in self._live_locked()}

    def member_loads(self):
        """{member id: inflight} for members in the routing rotation
        (the autoscaler's idle-detection input)."""
        with self._lock:
            return {m.id: m.inflight for m in self._members.values()
                    if m.state in ("live", "canary")}

    def shed_signal(self):
        """Cumulative fleet-wide sheds: router-local quota refusals
        plus the aggregated worker-side shed counter (only non-zero
        when members ship snapshots) — the autoscaler's shed-rate
        input."""
        return float(self._sheds) + self._aggregator.counter_value(
            "paddle_serving_shed_total")

    def attach_autoscaler(self, scaler):
        """Attach (or detach, with None) the capacity controller the
        monitor loop ticks. The router never constructs one — default
        flags construct no autoscaler, and the monitor's gate is one
        attribute-is-None check."""
        self._autoscaler = scaler

    def retire_member(self, mid, drain_timeout=10.0, stop_timeout=5.0):
        """Drain ``mid`` and take it out of the fleet — the scale-down
        path (also an operator verb): stop routing new work to it,
        wait out its in-flight requests, send ``stop`` (a subprocess
        worker's serve_forever unblocks, closes, and unregisters), and
        force-drop if the worker doesn't surrender its lease in time.
        Not a death: no death counter, no flight dump. Returns False
        when the member is unknown or already dead."""
        with self._lock:
            m = self._members.get(mid)
            if m is None or m.state == "dead":
                return False
        self._drain_member(m, drain_timeout)
        self._member_call(m, {"cmd": "stop"}, timeout=stop_timeout)
        deadline = time.monotonic() + stop_timeout
        while time.monotonic() < deadline:
            with self._lock:
                if m.state == "dead":
                    return True
            time.sleep(0.02)
        self._drop_member(mid, reason="retired", death=False)
        return True

    def fleet_doc(self):
        """The ``/debug/fleet`` document: membership, generation,
        per-member breaker/load state, and telemetry snapshot ages in
        one JSON-ready dict."""
        with self._lock:
            members = {}
            for m in self._members.values():
                members[m.id] = {
                    "state": m.state,
                    "version": m.version,
                    "addr": list(m.addr),
                    "joined_generation": m.joined_gen,
                    "inflight": m.inflight,
                    "served": m.served,
                    "failures": m.failures,
                    "breaker": None if m.breaker is None
                    else m.breaker.state,
                }
                if m.residency.models or m.active_model is not None:
                    members[m.id]["residency"] = m.residency.doc()
                    members[m.id]["active_model"] = m.active_model
            doc = {
                "router": "f%d" % self._rid,
                "generation": self._generation,
                "live": len(self._live_locked()),
                "members_min": self.members_min,
                "canary": self._canary,
                "closed": self._closed,
                "members": members,
            }
            if self._tenants is not None:
                doc["tenants"] = {
                    t.id: {"quota": t.quota, "priority": t.priority,
                           "active": t.active, "sheds": t.sheds}
                    for t in self._tenants.values()}
                doc["sheds"] = self._sheds
            if self.member_inflight_limit:
                doc["member_inflight_limit"] = \
                    self.member_inflight_limit
            if self._catalog is not None:
                doc["models"] = self._catalog.doc()
                doc["resident_bytes_budget"] = self.resident_bytes
                doc["paging"] = sorted(self._paging)
        scaler = self._autoscaler
        if scaler is not None:
            doc["autoscale"] = scaler.doc()
        telemetry = self._aggregator.fleet_doc()
        for mid, tstate in telemetry["members"].items():
            members.setdefault(mid, {"state": "retired"})[
                "telemetry"] = tstate
        doc["telemetry"] = {k: v for k, v in telemetry.items()
                            if k != "members"}
        if self.slo is not None:
            doc["slo"] = {"alerting": self.slo.alerting,
                          "violation_seconds":
                          round(self.slo.violation_seconds, 3)}
        return doc

    def wait_members(self, n=None, timeout=30.0):
        """Block until ``n`` members (default ``members_min``) are in
        rotation — the bring-up rendezvous, fleet tier."""
        n = self.members_min if n is None else int(n)
        deadline = time.monotonic() + timeout
        while True:
            live = self.members_live()
            if len(live) >= n:
                return live
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    "fleet rendezvous timed out: %d of %d members "
                    "joined (%r)" % (len(live), n, live))
            time.sleep(0.02)

    # -- membership (control plane) ---------------------------------------
    def _control(self, conn, msg):
        cmd = msg.get("cmd")
        if cmd == "reg":
            conn.send(self._register(msg))
        elif cmd == "hb":
            conn.send(self._heartbeat(msg))
        elif cmd == "unreg":
            conn.send(self._unregister(msg))
        elif cmd == "metrics":
            conn.send(self._ingest_metrics(msg))
        elif cmd == "members":
            with self._lock:
                conn.send({"ok": True, "generation": self._generation,
                           "members": sorted(
                               m.id for m in self._live_locked())})
        else:
            conn.send({"ok": False, "error": "unknown cmd %r" % cmd})

    def _register(self, msg):
        mid = str(msg.get("member"))
        addr = msg.get("addr")
        if not mid or not addr:
            return {"ok": False, "error": "reg needs member and addr"}
        with self._lock:
            if self._closed:
                return {"ok": False, "error": "router closed"}
            cur = self._members.get(mid)
            if cur is not None and cur.state != "dead" \
                    and cur.addr == tuple(addr):
                # re-register (restarted heartbeat / GENMISMATCH
                # recovery): membership unchanged, no bump
                cur.deadline = time.monotonic() + self.heartbeat_timeout
                gen = self._generation
                member = cur
                fresh = False
            else:
                # a genuinely new member (or a dead id returning, or a
                # relocated address — a new process either way) bumps
                # the generation so stale world views are fenced
                self._generation += 1
                gen = self._generation
                member = _Member(mid, addr, gen, self._label(mid),
                                 next(self._member_seq))
                member.deadline = time.monotonic() + \
                    self.heartbeat_timeout
                member.version = msg.get("version")
                if self.breaker_failures:
                    member.breaker = ReplicaBreaker(
                        member.index, self.breaker_failures,
                        self.breaker_cooldown, label=member.label)
                self._members[mid] = member
                fresh = True
            # residency advertisement rides the REG like the version:
            # a model-less worker sends no "models" field at all, so
            # legacy frames stay byte-identical
            if msg.get("models") is not None:
                member.residency.update(msg.get("models"), gen,
                                        self._catalog)
                if msg.get("active_model") is not None:
                    member.active_model = str(msg["active_model"])
                resident_bytes = member.residency.nbytes()
            else:
                resident_bytes = None
            live = len(self._live_locked())
            self._gauge("generation").set(self._generation)
            self._gauge("live").set(live)
        _MEMBER_INFLIGHT.labels(member=member.label).set(
            member.inflight)
        if resident_bytes is not None:
            _paging.RESIDENT_BYTES.labels(member=member.label).set(
                resident_bytes)
        if fresh:
            _log.structured("fleet_member_joined", member=mid,
                            generation=gen, live=live,
                            addr=list(member.addr))
            _rtrace.global_event("fleetMemberJoin", member=mid,
                                 generation=gen)
        return {"ok": True, "generation": gen, "live": live}

    def _heartbeat(self, msg):
        mid = str(msg.get("member"))
        gen = msg.get("generation")
        with self._lock:
            m = self._members.get(mid)
            if m is None or m.state == "dead":
                # a restarted router (or a reaped member): re-register
                return {"ok": False, "genmismatch": self._generation}
            # a GENMISMATCH beat still refreshes liveness (PR-6 rule:
            # the beat proves the process is alive; the fence only
            # says its world view is stale)
            m.deadline = time.monotonic() + self.heartbeat_timeout
            known = True
            mismatch = gen != self._generation
            generation = self._generation
            # residency rides the beat, fenced by generation exactly
            # like the world view it belongs to: a stale beat's
            # advertisement is ignored (the member re-registers and
            # re-advertises at the current generation)
            resident_bytes = None
            if not mismatch and msg.get("models") is not None:
                m.residency.update(msg.get("models"), generation,
                                   self._catalog)
                if msg.get("active_model") is not None:
                    m.active_model = str(msg["active_model"])
                resident_bytes = m.residency.nbytes()
            label = m.label
        if resident_bytes is not None:
            _paging.RESIDENT_BYTES.labels(member=label).set(
                resident_bytes)
        # piggybacked registry snapshot: ingested outside the router
        # lock (the aggregator has its own), and even on a fenced
        # beat — a stale world view does not stale the numbers
        snap = msg.get("metrics")
        if known and snap is not None:
            try:
                self._aggregator.ingest(mid, msg.get("incarnation"),
                                        snap)
            except ValueError:
                pass  # unreadable snapshot; the beat itself counted
        if mismatch:
            return {"ok": False, "genmismatch": generation}
        return {"ok": True, "generation": generation}

    def _ingest_metrics(self, msg):
        """The standalone ``metrics`` verb: an out-of-band snapshot
        push (a closing worker's final ship, probes, tests)."""
        mid = str(msg.get("member"))
        with self._lock:
            m = self._members.get(mid)
            if m is None:
                return {"ok": False,
                        "error": "unknown member %r" % mid}
        try:
            merged = self._aggregator.ingest(
                mid, msg.get("incarnation"), msg.get("snapshot"))
        except ValueError as exc:
            return {"ok": False, "error": repr(exc)[:200]}
        if m.state == "dead":
            # a final ship from an already-dropped member: the counts
            # land (conservation), the staleness clock stays running
            self._aggregator.mark_dead(mid)
        return {"ok": True, "families": merged}

    def _unregister(self, msg):
        mid = str(msg.get("member"))
        self._drop_member(mid, reason="unregister", death=False)
        return {"ok": True, "generation": self._generation}

    def _monitor_loop(self):
        tick = min(0.5, max(0.01, self.heartbeat_timeout / 4.0))
        while not self._monitor_stop.wait(tick):
            burn = None
            if self.slo is not None:
                # the tracker is pull-based; the membership monitor is
                # its clock (verdict() also ticks, so a pull-only
                # router without a monitor thread still works)
                burn = self.slo.tick()
                for tracker in self._tenant_slos.values():
                    tracker.tick()
                for tracker in self._model_slos.values():
                    tracker.tick()
            scaler = self._autoscaler
            if scaler is not None:
                # the capacity control loop rides the membership
                # monitor (no thread of its own); its spawns/retires
                # run on daemon threads, so a wedged launch can never
                # stall heartbeat reaping
                try:
                    scaler.tick(burn=burn)
                except Exception as exc:
                    _log.structured("autoscale_tick_error",
                                    error=repr(exc)[:200])
            if self.resident_bytes > 0:
                # re-apply eviction pressure to members still over
                # the byte budget: the page-in-time pass skips pinned
                # victims (in-flight requests), so the monitor is
                # where pressure lands once the pins drain
                with self._lock:
                    over = [m for m in self._members.values()
                            if m.state in ("live", "canary") and
                            m.residency.nbytes() > self.resident_bytes]
                for m in over:
                    self._evict_pressure(m)
            now = time.monotonic()
            with self._lock:
                overdue = [m.id for m in self._members.values()
                           if m.state != "dead" and m.deadline
                           is not None and now >= m.deadline]
            for mid in overdue:
                self._drop_member(mid, reason="heartbeat_timeout")

    def _drop_member(self, mid, reason, death=True):
        """Declare ``mid`` dead: bump the generation, retire its
        gauges (the stale-label sweep), and shut its open request
        connections down so blocked request threads fail over NOW
        instead of waiting out their read timeout."""
        with self._lock:
            m = self._members.get(mid)
            if m is None or m.state == "dead":
                return
            m.state = "dead"
            self._generation += 1
            gen = self._generation
            conns = list(m.conns)
            m.conns.clear()
            live = len(self._live_locked())
            self._gauge("generation").set(gen)
            self._gauge("live").set(live)
        if death:
            _DEATHS.inc()
        # telemetry: its snapshot stays, staleness-labeled, for a
        # bounded number of windows (conservation already banked)
        self._aggregator.mark_dead(mid)
        if m.breaker is not None:
            m.breaker.retired = True  # no gauge resurrection
        # stale-label hygiene: every family labelled on this member —
        # breaker health ("replica") and inflight ("member") — retires
        # in one sweep per labelname (the PR-12 scheduler-close rule)
        _metrics.REGISTRY.remove_labeled("replica", value=m.label)
        _metrics.REGISTRY.remove_labeled("member", value=m.label)
        _log.structured("fleet_member_dropped", member=mid,
                        reason=reason, generation=gen, live=live)
        _rtrace.global_event("fleetMemberDeath", member=mid,
                             reason=reason, generation=gen)
        if death:
            _flight.RECORDER.trigger_async("fleet_member_death",
                                           member=mid, cause=reason)
        for conn in conns:
            conn.close()  # SHUT_RDWR: recv-blocked threads unblock

    # -- request plane ----------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               deadline_ms=None, meta=False, seed=None, tenant=None,
               model=None):
        """Route one generation request over the fleet; returns a
        Future of the generated ids (int64 array), or — with
        ``meta=True`` — of ``{"tokens", "version", "version_start",
        "member", "replays"}`` (the deploy-proof surface: a response
        is served by exactly one weights version). ``seed`` keys a
        sampled decode policy on the members; minted here when None —
        ALWAYS, because the router cannot know which policy members
        run, and an unseeded sampled journal could never re-drive
        bit-identically after a member death.

        ``tenant`` names the submitting tenant: with a tenant table
        armed it is admission-checked against that tenant's quota
        (:class:`TenantQuotaError` when over — ITS traffic sheds, not
        the fleet's) and carried end-to-end on every hop's envelope;
        without a table it rides along for tracing only.

        ``model`` names the catalog model this request targets
        (catalog-armed routers only; defaults to the tenant's catalog
        mapping). The request routes residency-first and demand-pages
        the model onto a member when nobody holds it."""
        if self._closed:
            raise RuntimeError("router is closed")
        if self._catalog is not None:
            if model is not None:
                model = str(model)
                if model not in self._catalog:
                    raise ValueError(
                        "model %r is not in the fleet catalog (%s)"
                        % (model, self._catalog.ids()))
            else:
                model = self._catalog.for_tenant(tenant)
        elif model is not None:
            raise ValueError(
                "submit(model=...) needs a model catalog "
                "(the fleet_models flag or FleetRouter(models=...))")
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        deadline = None
        if deadline_ms:
            budget = float(deadline_ms) / 1e3
            if budget < 0:
                _sres.DEADLINE_EXCEEDED.inc()
                raise ServingDeadlineError(
                    "deadline budget %.1f ms already spent"
                    % float(deadline_ms))
            deadline = time.monotonic() + budget
        req = _FleetRequest(prompt, max_new_tokens, eos_id, deadline,
                            meta,
                            seed=mint_seed() if seed is None else seed,
                            tenant=tenant, model=model)
        if self._tenants is not None:
            req.tenant_entry = self._admit_tenant(req.tenant)
        mint_kw = {}
        if req.tenant is not None:
            mint_kw["tenant"] = req.tenant
        if req.model is not None:
            mint_kw["model"] = req.model
        req.ctx = _rtrace.mint("fleet.submit",
                               prompt_len=int(prompt.size),
                               router=self._rid, **mint_kw)
        _REQUESTS.inc()
        threading.Thread(target=self._serve, args=(req,), daemon=True,
                         name="fleet-request").start()
        return req.future

    def _admit_tenant(self, tenant):
        """Quota admission against the tenant table (table armed ==
        caller guaranteed ``self._tenants is not None``). Unknown
        tenants get a row lazily under the ``"*"`` policy, so every
        tenant is metered whether or not the operator named it.
        Raises :class:`TenantQuotaError` — typed, so callers can tell
        "you are bursting" from "the fleet is full" — and charges the
        shed to THIS tenant's counters plus the fleet-wide shed total
        (a quota refusal IS fleet SLO pressure: it feeds the
        autoscaler's shed-rate signal)."""
        tid = "default" if tenant is None else str(tenant)
        with self._lock:
            entry = self._tenants.get(tid)
            if entry is None:
                quota, priority = self._tenant_default
                entry = _Tenant(tid, quota, priority,
                                "f%d:%s" % (self._rid, tid))
                self._tenants[tid] = entry
            shed = entry.quota > 0 and entry.active >= entry.quota
            if shed:
                entry.sheds += 1
                self._sheds += 1
            else:
                entry.active += 1
                active = entry.active
        if shed:
            _sres.SHED.inc()
            _sres.TENANT_SHED.labels(tenant=entry.label).inc()
            raise TenantQuotaError(
                tid, "tenant %r over its in-flight quota (%d)"
                % (tid, entry.quota))
        _TENANT_ACTIVE.labels(tenant=entry.label).set(active)
        return entry

    def _tenant_done(self, req):
        """Release the admission slot a resolved request held."""
        entry = req.tenant_entry
        if entry is None:
            return
        req.tenant_entry = None
        with self._lock:
            entry.active = max(0, entry.active - 1)
            active = entry.active
        _TENANT_ACTIVE.labels(tenant=entry.label).set(active)

    def _resolve_ok(self, req):
        toks = req.tokens
        if req.eos_id is not None and toks and toks[-1] == req.eos_id:
            toks = toks[:-1]
        e2e = time.perf_counter() - req.t_submit
        _REQUEST_MS.observe(e2e * 1e3)
        if req.tenant_entry is not None:
            _TENANT_REQUEST_MS.labels(
                tenant=req.tenant_entry.label).observe(e2e * 1e3)
        if req.model is not None and self._catalog is not None:
            _paging.MODEL_REQUEST_MS.labels(
                model="f%d:%s" % (self._rid, req.model)).observe(
                e2e * 1e3)
        self._tenant_done(req)
        if req.ctx is not None:
            _rtrace.event(req.ctx, "resolve", tokens=len(toks),
                          member=req.member, replays=req.replays,
                          dur_ms=e2e * 1e3)
        arr = np.asarray(toks, np.int64)
        if req.meta:
            _resolve(req.future, result={
                "tokens": arr, "version": req.version,
                "version_start": req.version_start,
                "member": req.member, "replays": req.replays})
        else:
            _resolve(req.future, result=arr)

    def _resolve_err(self, req, exc):
        if req.tenant_entry is not None and \
                isinstance(exc, ServingDeadlineError):
            # the per-tenant bad count (the global DEADLINE_EXCEEDED
            # was already charged at the expiry site)
            _TENANT_DEADLINE.labels(
                tenant=req.tenant_entry.label).inc()
        if req.model is not None and self._catalog is not None and \
                isinstance(exc, ServingDeadlineError):
            _paging.MODEL_DEADLINE.labels(
                model="f%d:%s" % (self._rid, req.model)).inc()
        self._tenant_done(req)
        if req.ctx is not None:
            _rtrace.event(req.ctx, "resolveError",
                          error=repr(exc)[:200],
                          error_type=type(exc).__name__)
        _resolve(req.future, exception=exc)

    def _serve(self, req):
        last_exc = None
        while True:
            if req.deadline is not None and \
                    time.monotonic() >= req.deadline:
                _sres.DEADLINE_EXCEEDED.inc()
                if req.ctx is not None:
                    _rtrace.event(req.ctx, "deadlineExpired",
                                  replays=req.replays)
                self._resolve_err(req, ServingDeadlineError(
                    "fleet deadline expired after %.1f ms"
                    % ((time.perf_counter() - req.t_submit) * 1e3)))
                return
            # a member died between streaming EOS and its done frame:
            # the journal already ends the generation — serve it
            # without another hop
            if req.eos_id is not None and req.tokens and \
                    req.tokens[-1] == req.eos_id:
                self._resolve_ok(req)
                return
            if req.remaining() == 0:
                self._resolve_ok(req)
                return
            if req.model is not None and self._catalog is not None:
                # residency-or-page-in BEFORE placement — this runs
                # on every loop iteration, so a journal whose model
                # was paged out (or whose only resident member was
                # SIGKILL'd mid-generation) re-pages the model on the
                # target member before the re-drive
                try:
                    if not self._ensure_resident(req):
                        self._resolve_err(req, _paging.PageInError(
                            "model %r could not be paged onto any "
                            "member" % req.model))
                        return
                except ServingDeadlineError as exc:
                    self._resolve_err(req, exc)
                    return
            m = self._acquire_member(req)
            if m is None:
                self._resolve_err(
                    req, last_exc if last_exc is not None
                    else ServingUnavailableError(
                        "no healthy fleet member"))
                return
            try:
                done = self._run_hop(req, m)
            except _VersionRetry:
                # router-side cache staleness, not a member failure:
                # the journal was reset, retry (from the prompt) with
                # no breaker charge and no replay burned
                continue
            except _ModelRetry:
                # the member no longer holds the request's model
                # (evicted between placement and dispatch): correct
                # the residency view and re-drive — _ensure_resident
                # re-pages first. Not a member failure: no breaker
                # charge, no replay burned, but bounded so a
                # pathological member can't spin the loop forever.
                with self._lock:
                    m.residency.drop(req.model)
                    if m.active_model == req.model:
                        m.active_model = None
                req.model_retries += 1
                if req.model_retries > max(3, self.replay_attempts):
                    self._resolve_err(req, _paging.PageInError(
                        "model %r kept vanishing from members that "
                        "advertised it" % req.model))
                    return
                continue
            except Exception as exc:
                # a read past call_timeout is a hang (socket.timeout
                # is TimeoutError): instant breaker open, the PR-5 rule
                hang = isinstance(exc, TimeoutError)
                self._member_failed(req, m, exc, hang=hang)
                last_exc = exc
                if req.replays >= self.replay_attempts:
                    self._resolve_err(req, exc)
                    return
                req.replays += 1
                req.fail_t = time.perf_counter()
                _FAILOVERS.inc()
                if req.ctx is not None:
                    _rtrace.event(req.ctx, "failoverRequeue",
                                  from_member=m.id,
                                  replays=req.replays,
                                  journal_len=len(req.journal()),
                                  error=repr(exc)[:200])
                continue
            if done:
                return

    # -- model paging (PR 20) ---------------------------------------------
    def _ensure_resident(self, req):
        """Make ``req.model`` resident on at least one live member,
        demand-paging it when nobody holds it. Returns True once a
        resident member exists (placement affinity takes it from
        there), False when paging failed within its budget.

        Exactly one page-in per model runs fleet-wide at a time: the
        first request through becomes the leader (an Event in
        ``self._paging`` is the election), peers wait on it — a burst
        of cold requests for one model costs one staged load, not a
        stampede of them."""
        model = req.model
        spec = self._catalog.get(model)
        attempts = 0
        budget = self.page_timeout if self.page_timeout > 0 else 30.0
        while True:
            if req.deadline is not None and \
                    time.monotonic() >= req.deadline:
                _sres.DEADLINE_EXCEEDED.inc()
                raise ServingDeadlineError(
                    "deadline expired waiting for model %r to page "
                    "in" % model)
            leader = False
            target = None
            with self._lock:
                if self._closed:
                    return False
                live = [m for m in self._members.values()
                        if m.state in ("live", "canary")]
                # a member this request already FAILED on may still
                # advertise residency (SIGKILL'd but not yet swept by
                # the heartbeat timeout): never count it — affinity
                # must not trap a replay on a corpse
                resident = [m for m in live
                            if m.residency.resident(model) and
                            m.id not in req.failed_on]
                if not req.model_counted:
                    req.model_counted = True
                    (_paging.RESIDENCY_HITS if resident
                     else _paging.RESIDENCY_MISSES).inc()
                if resident:
                    return True
                evt = self._paging.get(model)
                if evt is None:
                    # leader election: page onto the least-loaded
                    # member with no page-in already in flight —
                    # exactly the spawn-target discipline, but the
                    # capacity being added is a weight set, not a
                    # process
                    cands = sorted(
                        (m for m in live if not m.paging),
                        key=lambda m: (m.id in req.failed_on,
                                       m.inflight, m.index))
                    if cands:
                        target = cands[0]
                        target.paging = True
                        evt = threading.Event()
                        self._paging[model] = evt
                        leader = True
            if leader:
                try:
                    ok = self._page_in(target, model, spec)
                finally:
                    with self._lock:
                        target.paging = False
                        evt = self._paging.pop(model, None)
                    if evt is not None:
                        evt.set()
                if ok:
                    self._evict_pressure(target)
                    return True
                attempts += 1
                if attempts >= 2:
                    return False
                continue
            if evt is not None:
                # follower: ride the leader's page-in, then re-check
                evt.wait(budget)
                continue
            # nobody to page onto (no live members / all mid-page):
            # wait out the placement window like _acquire_member does
            attempts += 1
            if attempts >= max(4, int(budget / 0.05)):
                return False
            time.sleep(0.05)

    def _page_in(self, m, model, spec):
        """One demand page-in on ``m``: the worker stages the
        artifact through its swap gates (manifest-verified load ->
        flip), bounded by ``model_page_timeout_ms``. A wedge or
        failure is charged to the autoscaler's spawn-failure budget —
        paging is capacity provisioning, and a wedging artifact must
        halt the control loop before it thrashes the fleet."""
        msg = {"cmd": "page_in", "model": model, "tag": spec.tag}
        if spec.params_path is not None:
            msg["params_path"] = spec.params_path
        if spec.model_dir is not None:
            msg["model_dir"] = spec.model_dir
        timeout = self.page_timeout if self.page_timeout > 0 else 30.0
        t0 = time.perf_counter()
        rep = self._member_call(m, msg, timeout=timeout)
        elapsed = time.perf_counter() - t0
        if rep.get("ok"):
            with self._lock:
                m.residency.add(model, spec.nbytes())
                m.active_model = str(rep.get("model") or model)
                m.version = rep.get("version", m.version)
                resident_bytes = m.residency.nbytes()
            _paging.RESIDENT_BYTES.labels(member=m.label).set(
                resident_bytes)
            _paging.PAGE_INS.labels(outcome="ok").inc()
            _paging.PAGE_IN_MS.observe(elapsed * 1e3)
            _log.structured("fleet_model_paged_in", member=m.id,
                            model=model, ms=round(elapsed * 1e3, 1))
            _rtrace.global_event("fleetModelPageIn", member=m.id,
                                 model=model)
            return True
        outcome = "timeout" if elapsed >= timeout else "fail"
        _paging.PAGE_INS.labels(outcome=outcome).inc()
        _log.structured("fleet_model_page_in_failed", member=m.id,
                        model=model, outcome=outcome,
                        error=str(rep.get("error"))[:200])
        scaler = self._autoscaler
        if scaler is not None:
            # the PR-18 budget: a wedged/failed page-in spends one
            # spawn failure — enough of them halts provisioning and
            # dumps a flight bundle instead of thrashing
            scaler.charge_failure("page_in")
        return False

    def _evict_pressure(self, m):
        """LRU page-outs until ``m``'s resident-set bytes fit the
        ``member_resident_bytes`` budget. NEVER a model with
        in-flight requests (the pin refcount — an invariant assert,
        not a counter) and never the active model; a fully-pinned
        over-budget set simply stays over budget until something
        drains."""
        if self.resident_bytes <= 0:
            return
        with self._lock:
            protect = ((m.active_model,)
                       if m.active_model is not None else ())
            victims = m.residency.lru_victims(self.resident_bytes,
                                              protect=protect)
        for victim in victims:
            try:
                # the race window under test: between victim
                # selection and the page-out, a request can pin the
                # victim — eviction must re-check, not race
                _faults.fire_point("model_evict_race", index=victim)
            except Exception:
                return  # injected abort: no page-out this round
            with self._lock:
                if victim == m.active_model or \
                        m.residency.pinned(victim) > 0:
                    continue  # pinned since selection: not a victim
                # the eviction invariant, asserted at the last gate
                # before the page-out leaves the router
                assert m.residency.pinned(victim) == 0, \
                    "evicting model %r with in-flight requests" \
                    % victim
                entry = m.residency.models.get(victim)
                nbytes = 0 if entry is None else entry.nbytes
                # drop from the routing view FIRST: from this instant
                # no new request can pin the victim on this member
                m.residency.drop(victim)
            rep = self._member_call(
                m, {"cmd": "page_out", "model": victim}, timeout=10.0)
            if not rep.get("ok"):
                with self._lock:
                    m.residency.add(victim, nbytes)
                continue
            with self._lock:
                resident_bytes = m.residency.nbytes()
            _paging.RESIDENT_BYTES.labels(member=m.label).set(
                resident_bytes)
            _paging.EVICTIONS.inc()
            _log.structured("fleet_model_evicted", member=m.id,
                            model=victim, resident_bytes=resident_bytes)
            _rtrace.global_event("fleetModelEvict", member=m.id,
                                 model=victim)

    def _acquire_member(self, req):
        """A member to dispatch to (inflight already counted), or
        None when nothing can take the request within the placement
        window. Least-loaded among eligible (live, breaker closed —
        or a cooldown-elapsed trial when nothing fitting is closed);
        members this request already failed on are last resort; a
        mid-deploy canary member receives only its traffic fraction.

        With a tenant table armed, placement is priority-tiered: a
        waiter yields while any STRICTLY higher-priority (lower
        number) waiter is queued, so under contention (a per-member
        inflight cap, or every breaker open) the high tier places
        first. No starvation guarantee beyond the waiter's own
        placement/deadline window — that is what priority means here.

        Every acquisition (and every placement timeout) folds its
        wait into ``place_wait_ewma`` — the batcher's queue-wait
        signal one tier up, and the autoscaler's load-rising input."""
        t_enter = time.monotonic()
        deadline = t_enter + self.placement_timeout
        if req.deadline is not None:
            deadline = min(deadline, req.deadline)
        prio = (0 if req.tenant_entry is None
                else req.tenant_entry.priority)
        with self._lock:
            self._waiters[prio] = self._waiters.get(prio, 0) + 1
        try:
            while True:
                if self._closed:
                    return None
                with self._lock:
                    behind = any(
                        p < prio and n > 0
                        for p, n in self._waiters.items())
                    m = None if behind else self._pick_locked(req)
                    if m is not None:
                        m.inflight += 1
                        if req.model is not None:
                            # the in-flight pin: from here to release
                            # this model can NEVER be an eviction
                            # victim on this member (BlockPool's
                            # refcount rule, weight-set sized)
                            m.residency.pin(req.model)
                            m.residency.touch(req.model)
                        _MEMBER_INFLIGHT.labels(member=m.label).set(
                            m.inflight)
                        return m
                    anyone = bool(self._live_locked())
                if self._closed and not anyone:
                    return None
                if time.monotonic() >= deadline:
                    return None
                # a breaker cooldown, a draining member, or a
                # scale-up registration can make someone eligible in
                # finite time
                time.sleep(0.02)
        finally:
            wait = time.monotonic() - t_enter
            with self._lock:
                n = self._waiters.get(prio, 1) - 1
                if n <= 0:
                    self._waiters.pop(prio, None)
                else:
                    self._waiters[prio] = n
                self.place_wait_ewma += _WAIT_ALPHA * (
                    wait - self.place_wait_ewma)

    def _pick_locked(self, req):
        live = [m for m in self._members.values()
                if m.state in ("live", "canary")]
        if self.member_inflight_limit > 0:
            # a full member is simply not a candidate — the request
            # queues at the router (measured by place_wait_ewma)
            # until someone drains or a scale-up joins
            live = [m for m in live
                    if m.inflight < self.member_inflight_limit]
        if not live:
            return None
        if req.model is not None:
            # residency affinity: members already holding the
            # request's model win placement outright (item 2's prefix
            # affinity, keyed on model id) — falling back to the full
            # set only when nobody holds it (the hop then pages in on
            # demand or errs kind="model" and re-drives)
            resident = [m for m in live
                        if m.residency.resident(req.model) and
                        m.id not in req.failed_on]
            live = resident or live
        canary = self._canary
        if canary is not None:
            if req.canary is None:
                # one routing decision per request: every k-th live
                # submission is canary-scoped (fraction-approximate,
                # deterministic — no RNG in the dispatch path)
                self._canary_tick += 1
                k = max(1, int(round(1.0 / max(self.canary_fraction,
                                               1e-6))))
                req.canary = (self._canary_tick % k) == 0
            if req.canary and canary not in req.failed_on:
                live = [m for m in live if m.id == canary] or live
            else:
                rest = [m for m in live if m.id != canary]
                live = rest or live
        cands = sorted(live, key=lambda m: (m.id in req.failed_on,
                                            m.inflight, m.index))
        if not cands:
            return None
        now = time.monotonic()
        # a cooldown-elapsed open breaker gets THIS request as its
        # trial even while healthy members exist — there is no
        # background prober at the fleet tier, so live traffic is how
        # an open member re-enters rotation (at most one trial per
        # cooldown window: a failed trial re-opens with a fresh one).
        # Never a request that already failed there.
        for m in cands:
            b = m.breaker
            if b is not None and b.state == "open" \
                    and b.ready_to_probe(now) \
                    and m.id not in req.failed_on:
                b.to_half_open()  # the dispatch IS the trial (PR 5)
                return m
        for m in cands:
            if m.breaker is None or m.breaker.state == "closed":
                return m
        for m in cands:
            if m.breaker.state == "half_open":
                return m  # nothing closed: trial traffic rides along
        return None

    def _release_member(self, m, model=None):
        with self._lock:
            m.inflight = max(0, m.inflight - 1)
            if model is not None:
                m.residency.unpin(model)
            inflight = m.inflight
            dead = m.state == "dead"
        if not dead:
            _MEMBER_INFLIGHT.labels(member=m.label).set(inflight)

    def _member_failed(self, req, m, exc, hang=False):
        b = m.breaker
        if b is not None:
            was_trial = b.state == "half_open"
            if hang or was_trial or not req.charged:
                # at most one charge per request across its replays —
                # a poison prompt cannot black out the fleet (PR 5/9);
                # hangs and trial failures always record
                b.record_failure(hang=hang)
                req.charged = True
        req.failed_on.add(m.id)
        req.canary = False  # a failed canary pin replays on the stable set
        with self._lock:
            m.failures += 1
        _log.structured("fleet_member_failed", member=m.id,
                        error=repr(exc)[:200], hang=hang,
                        replays=req.replays)

    def _run_hop(self, req, m):
        """One dispatch to ``m``: stream tokens into the journal until
        done. Returns True when the request was RESOLVED (success or a
        client-shaped error); raises on member failure (failover
        material). The inflight count is released either way."""
        try:
            _faults.fire_point("fleet_network_partition", index=m.id,
                               default_exc=ConnectionError)
            # when the hop names a model the member holds but isn't
            # serving, the worker activates it before acking — the
            # cached version/model say nothing about THIS hop, so the
            # pre-hop fences stand down and the ack checks decide
            will_activate = (req.model is not None and
                             m.active_model is not None and
                             m.active_model != req.model)
            if req.tokens and req.tokens_model is not None and \
                    req.model is None and \
                    m.active_model is not None and \
                    req.tokens_model != m.active_model:
                # the model fence, cached side: a journal generated
                # on one model must never splice onto another — a
                # two-model fleet serving model-less requests resets
                # here instead of mixing models in one response
                _JOURNAL_RESETS.labels(reason="model").inc()
                if req.ctx is not None:
                    _rtrace.event(req.ctx, "journalReset",
                                  from_model=req.tokens_model,
                                  to_model=m.active_model,
                                  discarded=len(req.tokens))
                req.tokens = []
            if req.tokens and not will_activate and \
                    req.tokens_version != m.version:
                # the journal was generated under different weights:
                # re-driving it here would splice two versions into
                # one response. Discard and restart from the prompt —
                # determinism makes the restart exact, versioning
                # makes it honest.
                _JOURNAL_RESETS.labels(reason="version").inc()
                if req.ctx is not None:
                    _rtrace.event(req.ctx, "journalReset",
                                  from_version=req.tokens_version,
                                  to_version=m.version,
                                  discarded=len(req.tokens))
                req.tokens = []
            if req.tokens and m.policy is not None and \
                    req.tokens_policy != m.policy:
                # same rule, decode semantics instead of weights: a
                # journal minted under one decode policy must never
                # resume under another (a greedy prefix spliced onto
                # a sampled continuation is neither policy's answer).
                # m.policy None = member never acked yet; the ack
                # recheck below covers that hop.
                _JOURNAL_RESETS.labels(reason="policy").inc()
                if req.ctx is not None:
                    _rtrace.event(req.ctx, "journalReset",
                                  from_policy=req.tokens_policy,
                                  to_policy=m.policy,
                                  discarded=len(req.tokens))
                req.tokens = []
            gen_at_dispatch = self._generation
            hop_span = None
            if req.ctx is not None:
                hop_span = _rtrace.event(
                    req.ctx, "fleetHop", member=m.id,
                    generation=gen_at_dispatch, attempt=req.replays,
                    journal_len=len(req.journal()))
            conn = _wire.LineConn.connect(m.addr,
                                          timeout=self.connect_timeout)
            conn.settimeout(self.call_timeout)
            with self._lock:
                if m.state == "dead":
                    conn.close()
                    raise _MemberError("member %s died before "
                                       "dispatch" % m.id)
                m.conns.add(conn)
            try:
                remaining_ms = None
                if req.deadline is not None:
                    remaining_ms = max(
                        1.0, (req.deadline - time.monotonic()) * 1e3)
                env = {"cmd": "generate",
                       "prompt": req.journal(),
                       "max_new": req.remaining(),
                       "eos_id": req.eos_id,
                       "seed": req.seed,
                       "deadline_ms": remaining_ms,
                       "trace_id": None if req.ctx is None
                       else req.ctx.trace_id}
                if req.tenant is not None:
                    # the tenant rides every hop like the seed: a
                    # replay lands on the peer still attributed to
                    # its tenant (worker-side sheds, traces)
                    env["tenant"] = req.tenant
                if req.model is not None:
                    # the model rides every hop too: the worker
                    # activates it (resident) or refuses the hop
                    # (kind="model" -> re-page and re-drive) — a
                    # journal never lands on the wrong weights
                    env["model"] = req.model
                conn.send(env)
                hop_start = len(req.tokens)
                ack_model = None
                while True:
                    msg = conn.recv()
                    if msg is None:
                        raise _MemberError(
                            "member %s closed mid-request (journal "
                            "at %d tokens)" % (m.id, len(req.tokens)))
                    ev = msg.get("ev")
                    if ev == "ack":
                        # the version this (possibly replay) hop
                        # STARTS under; the done frame must match it
                        # — the exactly-one-version proof surface
                        ack_version = msg.get("version")
                        ack_policy = msg.get("policy",
                                             GREEDY_FINGERPRINT)
                        ack_model = msg.get("model")
                        req.version_start = ack_version
                        if req.eos_id is None and \
                                msg.get("eos_id") is not None:
                            req.eos_id = int(msg["eos_id"])
                        with self._lock:
                            m.version = ack_version or m.version
                            m.policy = ack_policy or m.policy
                            if ack_model is not None:
                                m.active_model = str(ack_model)
                                if m.residency.resident(ack_model):
                                    m.residency.touch(ack_model)
                                else:
                                    nb = 0
                                    if self._catalog is not None and \
                                            str(ack_model) in \
                                            self._catalog:
                                        nb = self._catalog.get(
                                            ack_model).nbytes()
                                    m.residency.add(ack_model, nb)
                        if req.tokens and \
                                req.tokens_model is not None and \
                                ack_model is not None and \
                                req.tokens_model != str(ack_model):
                            # the model fence, authoritative side:
                            # the ack names the model this hop will
                            # actually decode under. A journal from
                            # another model is discarded BEFORE any
                            # of this hop's tokens land — counted
                            # under reason="model", and the request
                            # restarts from the prompt.
                            _JOURNAL_RESETS.labels(
                                reason="model").inc()
                            if req.ctx is not None:
                                _rtrace.event(
                                    req.ctx, "journalReset",
                                    from_model=req.tokens_model,
                                    to_model=str(ack_model),
                                    discarded=len(req.tokens),
                                    at="ack")
                            del req.tokens[:]
                            raise _VersionRetry()
                        if req.tokens and \
                                req.tokens_policy != ack_policy:
                            # the authoritative decode-policy check:
                            # the cached check above can miss a
                            # member whose policy the router never
                            # learned (fresh join, restart). Same
                            # abandon-and-retry as a version skew —
                            # no spliced-policy response, ever.
                            _JOURNAL_RESETS.labels(
                                reason="policy").inc()
                            if req.ctx is not None:
                                _rtrace.event(
                                    req.ctx, "journalReset",
                                    from_policy=req.tokens_policy,
                                    to_policy=ack_policy,
                                    discarded=len(req.tokens),
                                    at="ack")
                            del req.tokens[:]
                            raise _VersionRetry()
                        if req.tokens and \
                                req.tokens_version != ack_version:
                            # the pre-hop check used the router's
                            # CACHED member version; the ack is
                            # authoritative (an out-of-band swap can
                            # stale the cache). The journal already
                            # went out under the wrong assumption —
                            # abandon the hop before any of its
                            # tokens land and retry from the prompt:
                            # a mixed-version response is never
                            # served, whoever swapped the member.
                            _JOURNAL_RESETS.labels(
                                reason="version").inc()
                            if req.ctx is not None:
                                _rtrace.event(
                                    req.ctx, "journalReset",
                                    from_version=req.tokens_version,
                                    to_version=ack_version,
                                    discarded=len(req.tokens),
                                    at="ack")
                            del req.tokens[:]
                            raise _VersionRetry()
                        if req.ctx is not None:
                            _rtrace.event(req.ctx, "memberRecv",
                                          parent=hop_span,
                                          member=msg.get("member"),
                                          pid=msg.get("pid"),
                                          version=msg.get("version"))
                    elif ev == "tok":
                        if req.fail_t is not None:
                            # kill-to-first-replayed-token: the fleet
                            # recovery number
                            _RECOVERY_SECONDS.observe(
                                time.perf_counter() - req.fail_t)
                            req.fail_t = None
                        req.tokens.append(int(msg["t"]))
                        req.tokens_version = m.version
                        req.tokens_policy = m.policy
                        req.tokens_model = (str(ack_model)
                                            if ack_model is not None
                                            else req.model)
                    elif ev == "done":
                        with self._lock:
                            fenced = m.state == "dead"
                        if fenced:
                            # the member was declared dead while this
                            # reply was in flight (partition healed):
                            # a dead member's word is never trusted —
                            # its streamed tokens go with it, and the
                            # request re-drives on a live peer (greedy
                            # determinism makes the re-drive exact)
                            del req.tokens[hop_start:]
                            _FENCED.inc()
                            if req.ctx is not None:
                                _rtrace.event(req.ctx, "fencedReply",
                                              member=m.id)
                            raise _MemberError(
                                "stale reply from dead member %s "
                                "fenced" % m.id)
                        # the done frame is authoritative for this
                        # hop's tokens (the stream includes an EOS the
                        # scheduler then strips; done does not)
                        req.tokens[hop_start:] = [
                            int(t) for t in msg.get("tokens", ())]
                        req.version = msg.get("version", m.version)
                        req.member = m.id
                        req.tokens_version = req.version
                        req.tokens_policy = m.policy
                        req.tokens_model = (str(ack_model)
                                            if ack_model is not None
                                            else req.model)
                        with self._lock:
                            m.served += 1
                            m.version = req.version
                        if m.breaker is not None:
                            m.breaker.record_success()
                        self._resolve_ok(req)
                        return True
                    elif ev == "err":
                        kind = msg.get("kind")
                        if kind == "deadline":
                            # the worker's deadline check fired: same
                            # condition, same exception type and
                            # counter as a router-side expiry
                            _sres.DEADLINE_EXCEEDED.inc()
                            if req.ctx is not None:
                                _rtrace.event(req.ctx,
                                              "deadlineExpired",
                                              where="member",
                                              member=m.id)
                            self._resolve_err(
                                req, ServingDeadlineError(
                                    msg.get("error", "")))
                            return True
                        if kind == "client":
                            # the request's fault (bucket/length):
                            # never charges the member, never replays
                            self._resolve_err(
                                req, ValueError(msg.get("error", "")))
                            return True
                        if kind == "model":
                            # the model isn't resident there after
                            # all (evicted between placement and
                            # dispatch): not a member failure — the
                            # serve loop re-pages and re-drives
                            raise _ModelRetry(msg.get("error", ""))
                        raise _MemberError(
                            "member %s failed the request: %s"
                            % (m.id, msg.get("error", "")))
            finally:
                with self._lock:
                    m.conns.discard(conn)
                conn.close()
        finally:
            self._release_member(m, req.model)

    # -- rolling deploy ---------------------------------------------------
    def _drain_member(self, m, timeout):
        with self._lock:
            if m.state == "dead":
                return False
            m.state = "draining"
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if m.state == "dead":
                    return False
                if m.inflight == 0:
                    return True
            time.sleep(0.01)
        return False

    def _member_call(self, m, msg, timeout=60.0):
        try:
            return _wire.call_once(m.addr, msg, timeout=timeout,
                                   retries=1)
        except (ConnectionError, OSError, _wire.WireError) as exc:
            return {"ok": False, "error": repr(exc)[:200]}

    def _rollback_members(self, mids, drain_timeout):
        _ROLLBACKS.inc()
        restored = []
        for mid in reversed(mids):
            with self._lock:
                m = self._members.get(mid)
            if m is None or m.state == "dead":
                continue
            self._drain_member(m, drain_timeout)
            rep = self._member_call(m, {"cmd": "rollback"})
            with self._lock:
                if m.state != "dead":
                    m.state = "live"
                    if rep.get("ok"):
                        m.version = rep.get("version", m.version)
            if rep.get("ok"):
                restored.append(mid)
                if m.breaker is not None and not m.breaker.retired:
                    # the rollback restored the version that was
                    # serving fine — failures charged to the bad push
                    # must not keep the healed member benched for a
                    # full cooldown
                    m.breaker.record_success()
            _log.structured("fleet_member_rolled_back", member=mid,
                            ok=bool(rep.get("ok")))
        return restored

    def rolling_deploy(self, params_path=None, tag=None,
                       model_dir=None, canary_requests=6,
                       watch_failures=2, watch_timeout=30.0,
                       drain_timeout=30.0, swap_timeout=120.0,
                       model_id=None):
        """Roll a weights push through the fleet, one member at a
        time: drain -> swap (the worker's PR-7/PR-9 gates apply) ->
        canary-scope ``canary_fraction`` of live traffic to it ->
        watch. ``watch_failures`` member-level failures during the
        watch (clients see none — canary failures replay onto stable
        members) roll the WHOLE fleet back to the prior version and
        abort. Returns a result dict; ``rolled_back`` tells the story.

        ``params_path`` (an ``.npz`` of {name: array}) feeds
        generation-scheduler workers; ``model_dir`` feeds stateless
        engine workers (``ServingEngine.swap_weights``).

        ``model_id`` scopes the deploy to one catalog model: only
        members RESIDENT for that model drain/swap/canary (each
        activates the model before applying the push), and other
        models' traffic rides on untouched — a multi-model fleet
        deploys one model without draining the others' members."""
        if not self._deploy_lock.acquire(blocking=False):
            raise RuntimeError("a rolling deploy is already running")
        try:
            model_id = None if model_id is None else str(model_id)
            with self._lock:
                order = sorted(
                    m.id for m in self._members.values()
                    if m.state == "live" and
                    (model_id is None or
                     m.residency.resident(model_id) or
                     m.active_model == model_id))
            if not order:
                return {"ok": False, "reason": "no live members"
                        if model_id is None else
                        "no live members resident for model %r"
                        % model_id,
                        "rolled_back": False, "swapped": []}
            swapped = []
            swap_msg = {"cmd": "swap", "tag": tag}
            if params_path is not None:
                swap_msg["params_path"] = str(params_path)
            if model_dir is not None:
                swap_msg["model_dir"] = str(model_dir)
            if model_id is not None:
                swap_msg["model"] = model_id
            _log.structured("fleet_deploy_start", tag=tag,
                            members=order, model=model_id)
            for mid in order:
                with self._lock:
                    m = self._members.get(mid)
                if m is None or m.state == "dead":
                    continue  # died mid-deploy: the survivors roll on
                if not self._drain_member(m, drain_timeout):
                    self._rollback_members(swapped, drain_timeout)
                    _DEPLOYS.labels(outcome="rolled_back").inc()
                    return {"ok": False, "rolled_back": True,
                            "reason": "drain timeout on %s" % mid,
                            "failed_member": mid, "swapped": swapped}
                rep = self._member_call(m, swap_msg,
                                        timeout=swap_timeout)
                if not rep.get("ok"):
                    with self._lock:
                        if m.state == "draining":
                            m.state = "live"
                    self._rollback_members(swapped, drain_timeout)
                    _DEPLOYS.labels(outcome="rolled_back").inc()
                    return {"ok": False, "rolled_back": True,
                            "reason": "swap rejected on %s: %s"
                            % (mid, rep.get("error")),
                            "failed_member": mid, "swapped": swapped}
                with self._lock:
                    m.version = rep.get("version", tag)
                    m.served = 0
                    m.failures = 0
                    m.state = "canary"
                    self._canary = mid
                    if model_id is not None:
                        # the worker activated model_id to apply the
                        # push — the router's view follows
                        m.active_model = model_id
                        m.residency.touch(model_id)
                swapped.append(mid)
                ok = self._watch_canary(m, canary_requests,
                                        watch_failures, watch_timeout)
                with self._lock:
                    self._canary = None
                    if m.state == "canary":
                        m.state = "live"
                if not ok:
                    self._rollback_members(swapped, drain_timeout)
                    _DEPLOYS.labels(outcome="rolled_back").inc()
                    _log.structured("fleet_deploy_rolled_back",
                                    tag=tag, failed_member=mid)
                    _flight.RECORDER.trigger_async(
                        "fleet_deploy_rollback", tag=str(tag),
                        member=mid)
                    return {"ok": False, "rolled_back": True,
                            "reason": "canary watch failed on %s"
                            % mid,
                            "failed_member": mid, "swapped": swapped}
            _DEPLOYS.labels(outcome="committed").inc()
            if model_id is not None and self._catalog is not None \
                    and tag is not None and \
                    model_id in self._catalog:
                # future page-ins of this model must land the pushed
                # version, not the pre-deploy artifact's tag
                self._catalog.get(model_id).tag = str(tag)
                if params_path is not None:
                    self._catalog.get(model_id).params_path = \
                        str(params_path)
                if model_dir is not None:
                    self._catalog.get(model_id).model_dir = \
                        str(model_dir)
            _log.structured("fleet_deploy_committed", tag=tag,
                            members=swapped)
            return {"ok": True, "rolled_back": False, "version": tag,
                    "swapped": swapped}
        finally:
            self._deploy_lock.release()

    def _watch_canary(self, m, canary_requests, watch_failures,
                      watch_timeout):
        """Watch the freshly-swapped member take its canary share:
        fail on ``watch_failures`` member-level failures (or its
        death), pass once ``canary_requests`` completions land — or
        at the watch timeout with zero failures (a quiet fleet can't
        prove more than 'nothing broke')."""
        deadline = time.monotonic() + watch_timeout
        while True:
            with self._lock:
                dead = m.state == "dead"
                served, failures = m.served, m.failures
            if dead or failures >= max(1, int(watch_failures)):
                return False
            if served >= int(canary_requests):
                return True
            if time.monotonic() >= deadline:
                return failures == 0
            time.sleep(0.02)

    # -- shutdown ---------------------------------------------------------
    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = [c for m in self._members.values()
                     for c in m.conns]
            for m in self._members.values():
                m.conns.clear()
                if m.breaker is not None:
                    m.breaker.retired = True
        self._monitor_stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        self._server.close()
        for conn in conns:
            conn.close()
        # router-namespace gauge sweep: every member-labelled child
        # ("f<rid>:*") across every family, plus the router's own
        # gauges — redeploy cycles must not accumulate stale labels
        prefix = "f%d:" % self._rid
        _metrics.REGISTRY.remove_labeled("replica", prefix=prefix)
        _metrics.REGISTRY.remove_labeled("member", prefix=prefix)
        _metrics.REGISTRY.remove_labeled("router",
                                         value="f%d" % self._rid)
        if self.slo is not None:
            self.slo.close()
        for tracker in self._tenant_slos.values():
            tracker.close()
        self._tenant_slos = {}
        for tracker in self._model_slos.values():
            tracker.close()
        self._model_slos = {}
        if self._tenants is not None:
            # per-tenant children share the router's label namespace
            _metrics.REGISTRY.remove_labeled("tenant", prefix=prefix)
        if self._catalog is not None:
            # per-model children share it too
            _metrics.REGISTRY.remove_labeled("model", prefix=prefix)
        scaler = self._autoscaler
        if scaler is not None:
            scaler.close()   # detaches itself; reaps pending spawns
        from ..observability import health as _health
        _health.unregister_health(self._health_name)
        for kind in ("metrics", "fleet", "slo"):
            _health.unregister_provider(kind, self._health_name)
        _flight.RECORDER.remove_context(self._health_name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _router_health(ref):
    def snapshot():
        router = ref()
        if router is None:
            return None
        with router._lock:
            members = {
                m.id: {"state": m.state, "version": m.version,
                       "inflight": m.inflight,
                       "breaker": None if m.breaker is None
                       else m.breaker.state}
                for m in router._members.values()}
            live = [m for m in router._members.values()
                    if m.state in ("live", "draining", "canary")]
            healthy_members = [
                m for m in live
                if m.breaker is None or m.breaker.state != "open"]
            return {"healthy": not router._closed and
                    len(healthy_members) >= router.members_min,
                    "generation": router._generation,
                    "live": len(live), "members": members}
    return snapshot


def _router_metrics(ref):
    """The /metrics provider: the fleet-merged exposition (or one
    member's drill-down; "" for an unknown member — None is reserved
    for "router gone", the lazy-unregister signal)."""
    def provider(member=None):
        router = ref()
        if router is None:
            return None
        text = router._aggregator.merged_text(member)
        if member and text is None:
            return ""
        return text
    return provider


def _router_fleet(ref):
    def provider():
        router = ref()
        return None if router is None else router.fleet_doc()
    return provider


def _router_slo(ref):
    def provider():
        router = ref()
        if router is None or router.slo is None:
            return None
        doc = router.slo.verdict()
        if router._tenant_slos:
            # per-tenant verdicts alongside the fleet one: /debug/slo
            # answers "whose p99 is blown" — the burster's, not the
            # victim's
            doc["tenants"] = {
                tid: tracker.verdict() for tid, tracker
                in sorted(router._tenant_slos.items())}
        if router._model_slos:
            # per-model verdicts too: paging churn on one model must
            # not paint its co-resident's verdict red
            doc["models"] = {
                model_id: tracker.verdict() for model_id, tracker
                in sorted(router._model_slos.items())}
        return doc
    return provider


def _router_flight_context(ref):
    """Flight-recorder context: a breaker-open / client-error bundle
    dumped at the router carries the fleet membership + SLO state
    that surrounded it."""
    def context():
        router = ref()
        if router is None:
            return None
        doc = {"fleet": router.fleet_doc()}
        if router.slo is not None:
            doc["slo"] = router.slo.verdict()
        return doc
    return context


class EngineWorker:
    """One fleet member: serves a local backend over the JSON-line
    wire and keeps its membership lease with the router.

    ``backend`` is a :class:`GenerationScheduler` (``generate``
    requests, token streaming, ``.npz`` weight swaps with host-side
    rollback snapshots) or a :class:`ServingEngine` (stateless
    ``run`` requests, ``model_dir`` swaps through the PR-7 gates).
    With ``router_addr`` set, the worker registers and heartbeats
    every ``heartbeat_ms`` (default: the ``fleet_heartbeat_ms`` flag)
    on a daemon thread — a ``genmismatch`` reply re-registers, a
    connection error is absorbed (the router may be restarting).
    ``fail_after_swap_tag`` is the chaos hook for deploy tests: a
    swap landing that tag arms a persistent ``generation_step_fail``
    (the stand-in for a broken weights push), disarmed again by the
    rollback that restores the prior version.

    ``model`` names the catalog model this worker starts resident
    for (multi-model fleets, PR 20). A model-named worker advertises
    its resident set + active model on REG and every heartbeat,
    answers ``page_in`` (manifest-verified staged load through the
    swap gates; the paged model becomes active) and ``page_out``
    (drops a non-active resident model's host snapshot), activates
    the model a ``generate`` envelope names (resident -> fast swap
    from the host snapshot; non-resident -> ``kind="model"`` error,
    the router re-pages and re-drives), and acks the model id — the
    router's third journal fence. ``model=None`` (default) sends
    none of these fields: legacy frames stay byte-identical.
    """

    def __init__(self, backend, host="127.0.0.1", port=0,
                 member_id=None, router_addr=None, heartbeat_ms=None,
                 version="v0", fail_after_swap_tag=None,
                 autostart=True, metrics_interval_ms=None,
                 model=None):
        self.backend = backend
        self._kind = ("generation" if hasattr(backend, "sessions")
                      else "engine")
        # the decode-policy fingerprint this member acks with: the
        # router gates journal reuse on it exactly as it gates on the
        # weights version. Computed once — the policy is immutable
        # for the scheduler's lifetime.
        fp = getattr(backend, "policy_fingerprint", None)
        self._policy_fp = fp() if callable(fp) else GREEDY_FINGERPRINT
        # seed forwarding is signature-gated: the router mints a seed
        # on EVERY request (it can't know member policies), but a
        # backend whose submit() predates decode policies (engines,
        # test fakes) must keep working untouched.
        try:
            params = inspect.signature(backend.submit).parameters
            self._accepts_seed = "seed" in params
            # tenant forwarding is gated the same way: a backend that
            # understands tenants gets the envelope's id (worker-side
            # shed attribution), older backends keep working untouched
            self._accepts_tenant = "tenant" in params
        except (TypeError, ValueError, AttributeError):
            self._accepts_seed = False
            self._accepts_tenant = False
        if self._kind == "engine":
            # the pre-deploy artifact dir IS the first swap's
            # rollback target — without it a failed first push has
            # nothing to roll back to
            self._cur_dir = getattr(backend, "model_dir", None)
        self.member_id = member_id or "w-%d" % os.getpid()
        self.router_addr = (tuple(router_addr)
                            if router_addr is not None else None)
        if heartbeat_ms is None:
            heartbeat_ms = _config.get_flag("fleet_heartbeat_ms")
        self.heartbeat = float(heartbeat_ms) / 1e3
        if metrics_interval_ms is None:
            metrics_interval_ms = _config.get_flag(
                "fleet_metrics_interval_ms")
        self.metrics_interval = float(metrics_interval_ms or 0.0) / 1e3
        # the delta-accounting identity: a restarted process carries a
        # fresh incarnation, so its zeroed totals re-base instead of
        # double-counting or regressing the fleet accumulators
        self.incarnation = "%d-%d" % (os.getpid(),
                                      next(_WORKER_INCARNATION_SEQ))
        self._next_ship = 0.0      # monotonic; 0 = first beat ships
        self.version = str(version)
        self.fail_after_swap_tag = fail_after_swap_tag
        self._prev = None          # (version, params/model_dir) snapshot
        self._armed_bad = False
        self._swap_lock = threading.Lock()
        # in-flight generation streams: a model activation (page-in,
        # demand activation, model-scoped deploy) drains this count
        # to zero under _swap_lock before swapping weights, so no
        # stream ever finishes its tokens on another model's weights
        self._gen_cv = threading.Condition()
        self._gen_active = 0
        # multi-model residency (PR 20): model id -> {"tag",
        # "params" (host snapshot, generation kind; None while the
        # weights live only in the scope), "model_dir" (engine
        # kind)}. The ACTIVE model's weights are in the backend; a
        # paged-but-inactive model is a host-side snapshot waiting
        # for a fast activation swap.
        self.model = None if model is None else str(model)
        self._resident = {}
        if self.model is not None:
            self._resident[self.model] = {
                "tag": self.version, "params": None,
                "model_dir": getattr(self, "_cur_dir", None)}
        self.generation = 0
        self._host, self._port = host, port
        self._server = None
        self._hb_stop = threading.Event()
        self._hb_thread = None
        self._stop_evt = threading.Event()
        if autostart:
            self.start()

    @property
    def addr(self):
        return self._server.addr

    def start(self):
        if self._server is not None:
            return self
        self._server = _wire.LineServer(
            self._handle, host=self._host, port=self._port,
            timeout=None, name="fleet-worker-%s" % self.member_id)
        if self.router_addr is not None:
            try:
                self._register()
            except BaseException:
                # a refused/unreachable registration must not leak
                # the accept thread + bound socket out of a failed
                # constructor (autostart callers never get a handle
                # to close)
                self._server.close()
                self._server = None
                raise
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True,
                name="fleet-hb-%s" % self.member_id)
            self._hb_thread.start()
        return self

    # -- membership -------------------------------------------------------
    def _residency_fields(self, msg):
        """Stamp the residency advertisement onto a REG/HB frame —
        only for model-named workers, so legacy frames stay
        byte-identical. Lock-free on purpose (the heartbeat must
        never stall behind a long page-in): a beat that races a
        mutation just skips the fields until the next one."""
        if self.model is None:
            return msg
        try:
            msg["models"] = sorted(self._resident)
            msg["active_model"] = self.model
        except RuntimeError:
            pass  # resident set mutating mid-iteration: next beat
        return msg

    def _register(self):
        rep = _wire.call_once(
            self.router_addr,
            self._residency_fields(
                {"cmd": "reg", "member": self.member_id,
                 "addr": list(self.addr), "version": self.version}),
            timeout=5.0, retries=5)
        if not rep.get("ok"):
            raise RuntimeError("fleet registration refused: %r" % rep)
        self.generation = int(rep["generation"])
        return self.generation

    def _hb_loop(self):
        beats = 0
        while not self._hb_stop.wait(self.heartbeat):
            beats += 1
            if _faults.should_fire("fleet_network_partition",
                                   self.member_id):
                continue  # injected partition: the beat never leaves
            msg = self._residency_fields(
                {"cmd": "hb", "member": self.member_id,
                 "generation": self.generation})
            if self.metrics_interval > 0:
                now = time.monotonic()
                if now >= self._next_ship:
                    # piggyback a registry snapshot, bounded so the
                    # frame NEVER breaches MAX_LINE — an oversize
                    # registry degrades the snapshot, not the beat
                    msg["metrics"] = _aggregate.build_snapshot(
                        max_bytes=_wire.MAX_LINE - 1024)
                    msg["incarnation"] = self.incarnation
                    self._next_ship = now + self.metrics_interval
            try:
                rep = _wire.call_once(self.router_addr, msg,
                                      timeout=2.0, retries=1)
            except (ConnectionError, OSError, _wire.WireError):
                continue  # router restarting/unreachable: keep beating
            if rep.get("ok"):
                continue
            if rep.get("genmismatch") is not None:
                # the fleet resized (or a restarted router forgot us):
                # re-register at the current generation
                try:
                    self._register()
                except (RuntimeError, ConnectionError, OSError):
                    pass

    # -- the wire ---------------------------------------------------------
    def _handle(self, conn, msg):
        cmd = msg.get("cmd")
        if cmd == "generate":
            return self._handle_generate(conn, msg)
        if cmd == "run":
            return self._handle_run(conn, msg)
        if cmd == "swap":
            conn.send(self._handle_swap(msg))
        elif cmd == "page_in":
            conn.send(self._handle_page_in(msg))
        elif cmd == "page_out":
            conn.send(self._handle_page_out(msg))
        elif cmd == "rollback":
            conn.send(self._handle_rollback())
        elif cmd == "health":
            rep = {"ok": True, "member": self.member_id,
                   "version": self.version, "pid": os.getpid()}
            if self.model is not None:
                rep["model"] = self.model
                rep["models"] = sorted(self._resident)
            conn.send(rep)
        elif cmd == "stop":
            conn.send({"ok": True})
            self._stop_evt.set()
        else:
            conn.send({"ok": False, "error": "unknown cmd %r" % cmd})

    def _handle_generate(self, conn, msg):
        # the slow-member site fires before ANY reply leaves — a
        # wedged member is silent, not chatty
        _faults.fire_point("fleet_slow_member", index=self.member_id)
        if self._kind != "generation":
            conn.send({"ev": "err", "kind": "client",
                       "error": "this member serves a stateless "
                       "engine — use cmd=run"})
            return
        trace_id = msg.get("trace_id")
        # adopt the ROUTER's trace id (wire propagation): when this
        # process has tracing armed, its own store grows the same
        # tree; either way the ack below carries the memberRecv info
        # back for the router's tree
        ctx = _rtrace.adopt(trace_id, "fleet.memberServe",
                            member=self.member_id) \
            if trace_id else None
        if ctx is not None:
            _rtrace.event(ctx, "memberRecv", member=self.member_id,
                          pid=os.getpid(), version=self.version)
        env_model = msg.get("model")
        with self._swap_lock:
            if env_model is not None:
                env_model = str(env_model)
                if env_model != self.model:
                    if env_model not in self._resident:
                        # paged out between the router's placement
                        # and this dispatch (the evict race): refuse
                        # — the router re-pages and re-drives, never
                        # decodes on the wrong weights
                        conn.send({
                            "ev": "err", "kind": "model",
                            "error": "model %r not resident on %s "
                            "(resident: %s)" % (
                                env_model, self.member_id,
                                sorted(self._resident))})
                        return
                    try:
                        # demand activation: fast swap from the host
                        # snapshot, through the same gates a deploy
                        # push takes
                        self._activate_locked(env_model)
                    except Exception as exc:
                        conn.send({"ev": "err", "kind": "model",
                                   "error": repr(exc)[:300]})
                        return
            # count this stream in while still under the swap lock:
            # an activation drains the count to zero before swapping
            # weights, and no new stream can pass this gate while an
            # activator holds the lock — a stream's tokens all come
            # from the model that was active when it was admitted
            with self._gen_cv:
                self._gen_active += 1
        try:
            self._stream_generation(conn, msg, ctx)
        finally:
            with self._gen_cv:
                self._gen_active -= 1
                self._gen_cv.notify_all()

    def _stream_generation(self, conn, msg, ctx):
        """The streaming half of a generate request, counted in
        ``_gen_active`` by the caller (:meth:`_handle_generate`)."""
        eos_id = msg.get("eos_id")
        if eos_id is None:
            eos_id = int(self.backend.sessions[0].spec.eos_id)
        ack = {"ev": "ack", "member": self.member_id,
               "pid": os.getpid(), "version": self.version,
               "policy": self._policy_fp,
               "eos_id": int(eos_id)}
        if self.model is not None:
            # the model id the router fences journals on: absent for
            # model-less workers, so legacy acks stay byte-identical
            ack["model"] = self.model
        conn.send(ack)
        tokq = queue.Queue()
        version_start = self.version
        kw = {}
        if self._accepts_seed and msg.get("seed") is not None:
            # the router-minted decode seed: re-fed verbatim on every
            # replay hop so a sampled generation resumes its exact
            # key schedule
            kw["seed"] = int(msg["seed"])
        if self._accepts_tenant and msg.get("tenant") is not None:
            kw["tenant"] = str(msg["tenant"])
        try:
            with _rtrace.activate(ctx):
                fut = self.backend.submit(
                    msg["prompt"], max_new_tokens=msg.get("max_new"),
                    eos_id=msg.get("eos_id"),
                    deadline_ms=msg.get("deadline_ms"),
                    on_token=tokq.put, **kw)
        except ServingDeadlineError as exc:
            conn.send({"ev": "err", "kind": "deadline",
                       "error": repr(exc)[:300]})
            return
        except ValueError as exc:
            conn.send({"ev": "err", "kind": "client",
                       "error": repr(exc)[:300]})
            return
        except Exception as exc:
            conn.send({"ev": "err", "kind": "server",
                       "error": repr(exc)[:300]})
            return
        streamed = 0
        try:
            while True:
                try:
                    t = tokq.get(timeout=0.05)
                except queue.Empty:
                    if fut.done() and tokq.empty():
                        break
                    continue
                streamed += 1
                # chaos: SIGKILL this member after streaming token N —
                # the deterministic mid-generation process death
                _faults.fire_point("fleet_member_kill", index=streamed)
                conn.send({"ev": "tok", "t": int(t)})
        except OSError:
            return  # client (router) went away mid-stream
        try:
            tokens = [int(t) for t in fut.result(timeout=0)]
        except Exception as exc:
            # "deadline" keeps its type across the wire (the router
            # re-raises ServingDeadlineError — the contract every
            # serving caller catches); "client" is the request's own
            # fault (never charged, never replayed)
            if isinstance(exc, ServingDeadlineError):
                kind = "deadline"
            elif isinstance(exc, ValueError):
                kind = "client"
            else:
                kind = "server"
            try:
                conn.send({"ev": "err", "kind": kind,
                           "error": repr(exc)[:300]})
            except OSError:
                pass
            return
        _WORKER_DONE.inc()  # this member's side of the ledger
        try:
            conn.send({"ev": "done", "tokens": tokens,
                       "member": self.member_id,
                       "version": self.version,
                       "version_start": version_start,
                       "streamed": streamed})
        except OSError:
            pass

    def _handle_run(self, conn, msg):
        _faults.fire_point("fleet_slow_member", index=self.member_id)
        if self._kind != "engine":
            conn.send({"ev": "err", "kind": "client",
                       "error": "this member serves a generation "
                       "scheduler — use cmd=generate"})
            return
        try:
            feed = {name: np.asarray(spec["data"],
                                     dtype=spec["dtype"])
                    for name, spec in msg["feed"].items()}
            outs = self.backend.run(
                feed, deadline_ms=msg.get("deadline_ms"))
            _WORKER_DONE.inc()
            conn.send({"ev": "done", "member": self.member_id,
                       "version": self.version,
                       "outputs": [{"data": np.asarray(o).tolist(),
                                    "dtype": str(np.asarray(o).dtype)}
                                   for o in outs]})
        except ValueError as exc:
            conn.send({"ev": "err", "kind": "client",
                       "error": repr(exc)[:300]})
        except Exception as exc:
            conn.send({"ev": "err", "kind": "server",
                       "error": repr(exc)[:300]})

    # -- model paging (PR 20) ---------------------------------------------
    def _activate_locked(self, model):
        """Make ``model`` (already resident) the active one: snapshot
        the outgoing model's live weights host-side, then swap the
        incoming snapshot in through the backend's gates. Caller
        holds ``_swap_lock``."""
        entry = self._resident[model]
        if self._kind == "generation":
            # drain in-flight streams first: the scheduler's swap
            # lands between decode steps, so without this a stream
            # admitted under the OUTGOING model would finish its
            # remaining tokens on the incoming model's weights —
            # cross-model output the version fence can't unmix
            with self._gen_cv:
                deadline = time.monotonic() + 60.0
                while self._gen_active:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise RuntimeError(
                            "activating %r timed out draining %d "
                            "in-flight generation stream(s)"
                            % (model, self._gen_active))
                    self._gen_cv.wait(left)
            params = entry["params"]
            if params is None:
                raise RuntimeError(
                    "model %r resident without a host snapshot"
                    % model)
            # the outgoing model keeps its weights: snapshot the live
            # values of exactly the vars about to be overwritten
            # (paged models share the program's parameter set — the
            # same contract a rolling-deploy push has)
            cur = self._resident.get(self.model)
            if cur is not None:
                scope = self.backend.sessions[0].scope
                snap = {}
                for name in params:
                    var = scope.find_var(name)
                    if var is not None:
                        snap[name] = np.array(var, copy=True)
                cur["params"] = snap
            self.backend.swap_weights(params)
        else:
            if entry.get("model_dir") is None:
                raise RuntimeError(
                    "model %r resident without an artifact dir"
                    % model)
            self.backend.swap_weights(entry["model_dir"])
            self._cur_dir = entry["model_dir"]
        self.model = model
        self.version = str(entry["tag"])

    def _handle_page_in(self, msg):
        model = str(msg.get("model"))
        tag = msg.get("tag") or "%s@v0" % model
        with self._swap_lock:
            inserted = False
            try:
                # chaos first: a wedged/failing page-in must look
                # exactly like a wedged staged load to the router
                _faults.fire_point("model_page_in_slow", index=model)
                _faults.fire_point("model_page_in_fail", index=model)
                if model == self.model:
                    pass  # already active: idempotent success
                elif model in self._resident:
                    # already resident (router raced itself or a
                    # stale view): just activate the snapshot
                    self._activate_locked(model)
                elif self._kind == "generation":
                    path = msg["params_path"]
                    # the manifest gate: a truncated or switched
                    # artifact is refused BEFORE any weight lands
                    _paging.verify_weights_manifest(path)
                    params = {k: np.asarray(v) for k, v in
                              np.load(path).items()}
                    self._resident[model] = {"tag": str(tag),
                                             "params": params,
                                             "model_dir": None}
                    inserted = True
                    self._activate_locked(model)
                else:
                    self._resident[model] = {
                        "tag": str(tag), "params": None,
                        "model_dir": msg["model_dir"]}
                    inserted = True
                    self._activate_locked(model)
            except Exception as exc:
                if inserted:
                    self._resident.pop(model, None)
                return {"ok": False, "error": repr(exc)[:300],
                        "version": self.version, "model": self.model}
        _log.structured("fleet_worker_paged_in",
                        member=self.member_id, model=model,
                        version=self.version,
                        resident=sorted(self._resident))
        return {"ok": True, "version": self.version,
                "model": self.model,
                "models": sorted(self._resident)}

    def _handle_page_out(self, msg):
        model = str(msg.get("model"))
        with self._swap_lock:
            if model == self.model:
                # the active model's weights live in the backend —
                # paging it out would leave the member serving
                # nothing (the router protects the active model, so
                # reaching this is a bug or a raced view)
                return {"ok": False, "version": self.version,
                        "error": "model %r is active" % model}
            if self._resident.pop(model, None) is None:
                return {"ok": False, "version": self.version,
                        "error": "model %r not resident" % model}
        _log.structured("fleet_worker_paged_out",
                        member=self.member_id, model=model,
                        resident=sorted(self._resident))
        return {"ok": True, "version": self.version,
                "models": sorted(self._resident)}

    # -- deploys ----------------------------------------------------------
    def _handle_swap(self, msg):
        tag = str(msg.get("tag"))
        with self._swap_lock:
            swap_model = msg.get("model")
            if swap_model is not None and \
                    str(swap_model) != self.model:
                # a model-scoped deploy lands on the named model, not
                # whatever happens to be active: activate it first
                # (resident members only — the router already scoped
                # the deploy order to them)
                swap_model = str(swap_model)
                if swap_model not in self._resident:
                    return {"ok": False, "version": self.version,
                            "error": "model %r not resident on %s"
                            % (swap_model, self.member_id)}
                try:
                    self._activate_locked(swap_model)
                except Exception as exc:
                    return {"ok": False, "error": repr(exc)[:300],
                            "version": self.version}
            try:
                if self._kind == "generation":
                    # host-side rollback snapshot of exactly the
                    # params the push names, taken BEFORE the swap
                    params = {k: np.asarray(v) for k, v in
                              np.load(msg["params_path"]).items()}
                    scope = self.backend.sessions[0].scope
                    snapshot = {}
                    for name in params:
                        cur = scope.find_var(name)
                        if cur is not None:
                            snapshot[name] = np.array(cur, copy=True)
                    self.backend.swap_weights(params)
                else:
                    # engine members roll back by re-swapping the
                    # prior artifact dir (PR-7 gates both ways)
                    snapshot = getattr(self, "_cur_dir", None)
                    self.backend.swap_weights(msg["model_dir"])
                    self._cur_dir = msg["model_dir"]
            except Exception as exc:
                return {"ok": False, "error": repr(exc)[:300],
                        "version": self.version}
            self._prev = (self.version, snapshot)
            prev_tag = self.version
            self.version = tag
            if self.model is not None:
                # the active model's resident entry tracks the push:
                # paging away and back must restore the PUSHED
                # weights, not the pre-deploy snapshot
                entry = self._resident.get(self.model)
                if entry is not None:
                    entry["tag"] = tag
                    if self._kind == "generation":
                        entry["params"] = params
                    else:
                        entry["model_dir"] = msg["model_dir"]
            if self._armed_bad:
                _faults.disarm("generation_step_fail")
                self._armed_bad = False
            if self.fail_after_swap_tag is not None and \
                    tag == str(self.fail_after_swap_tag):
                # deploy-chaos hook: this push is "broken" — every
                # decode step on it fails until a rollback restores
                # the prior version
                _faults.arm("generation_step_fail", times=None)
                self._armed_bad = True
            _log.structured("fleet_worker_swapped",
                            member=self.member_id, version=tag,
                            prev=prev_tag)
            return {"ok": True, "version": self.version}

    def _handle_rollback(self):
        with self._swap_lock:
            if self._prev is None:
                return {"ok": False, "error": "nothing to roll back",
                        "version": self.version}
            prev_tag, snapshot = self._prev
            if snapshot is None:
                return {"ok": False, "version": self.version,
                        "error": "no prior weights snapshot"}
            try:
                self.backend.swap_weights(snapshot)
            except Exception as exc:
                return {"ok": False, "error": repr(exc)[:300],
                        "version": self.version}
            self.version = prev_tag
            self._prev = None
            if self.model is not None:
                # the rollback restored the prior weights: the
                # active model's resident entry follows
                entry = self._resident.get(self.model)
                if entry is not None:
                    entry["tag"] = prev_tag
                    if self._kind == "generation":
                        entry["params"] = snapshot
                    else:
                        entry["model_dir"] = snapshot
            if self._armed_bad:
                _faults.disarm("generation_step_fail")
                self._armed_bad = False
            _log.structured("fleet_worker_rolled_back",
                            member=self.member_id, version=prev_tag)
            return {"ok": True, "version": self.version}

    # -- lifecycle --------------------------------------------------------
    def serve_forever(self):
        """Block until a ``stop`` command (or :meth:`close`) — the
        child-process entry point."""
        self._stop_evt.wait()
        self.close()

    def close(self):
        self._stop_evt.set()
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None
        if self.router_addr is not None:
            if self.metrics_interval > 0:
                # final ship: the counts earned since the last beat
                # land before the membership lease is surrendered
                try:
                    _wire.call_once(
                        self.router_addr,
                        {"cmd": "metrics", "member": self.member_id,
                         "incarnation": self.incarnation,
                         "snapshot": _aggregate.build_snapshot(
                             max_bytes=_wire.MAX_LINE - 1024)},
                        timeout=2.0, retries=1)
                except (ConnectionError, OSError, _wire.WireError):
                    pass
            try:
                _wire.call_once(self.router_addr,
                                {"cmd": "unreg",
                                 "member": self.member_id},
                                timeout=1.0, retries=1)
            except (ConnectionError, OSError, _wire.WireError):
                pass
        if self._armed_bad:
            _faults.disarm("generation_step_fail")
            self._armed_bad = False
        if self._server is not None:
            self._server.close()
            self._server = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
