"""Deploy resilience: AOT-exported serving artifacts + hot weight swap.

The reference deploy story is "restart the process and re-read the
protobuf" — every replica start re-traces and re-compiles every bucket.
This module makes deploys first-class:

* :func:`export_compiled_buckets` — called by
  ``io.save_inference_model(..., export_compiled=True)``: AOT-compiles
  each serving bucket of the just-exported artifact and embeds the
  serialized XLA executables under ``compiled/`` (one
  ``bucket_<b>.bin`` per bucket + an ``index.json`` with per-blob
  sha256 digests, the compile-environment fingerprint, and the
  executor cache digest that proves "this executable IS the
  computation you would compile"). A ServingEngine cold start then
  *deserializes* instead of compiling; any skew — jax version, flags,
  topology, corrupt blob — degrades to the normal compile path with a
  counter, never an error.
* :class:`SwapRejectedError` + the swap/rollback counters backing
  ``ServingEngine.swap_weights`` (engine.py): a new weight push is
  digest-verified, signature-checked, and canary-executed before the
  atomic flip, and a post-flip error burst auto-rolls back to the
  prior weights.
* :func:`write_weights_manifest` / :func:`verify_weights_manifest`
  (re-exported from serving/model_paging.py): the sha256 + per-var
  shape/dtype sidecar beside an ``.npz`` weights artifact that makes
  a fleet page-in a *manifest-verified* staged load — a truncated or
  switched artifact is refused before any weight touches a scope.

Fault sites (resilience/faults.py): ``swap_bad_artifact`` (fires in
swap validation), ``swap_canary_fail`` (fires before the canary run);
together with ``cache_corrupt`` (core/compile_cache.py) they make the
whole deploy layer chaos-testable — ``tools/deploy_probe.py`` drives
all three headless.

Metrics (always-on; deploys are rare events, never a per-request
cost): ``paddle_deploy_aot_loads_total`` /
``paddle_deploy_aot_fallbacks_total``,
``paddle_deploy_swap_total`` / ``paddle_deploy_swap_rolled_back_total``
(canary/validation rejections count as rollbacks — operationally both
are "the push did not land"), ``paddle_deploy_cold_start_seconds``
(engine construction through warmup), and the
``paddle_deploy_swap_blackout_seconds`` histogram (the longest time
any single replica was flip-locked — the per-replica serving blackout
of a swap).
"""

import json
import os

import numpy as np

import jax

from .. import config as _config
from ..core import compile_cache as _cc
from ..core.executor import Executor
from ..observability import metrics as _metrics
from ..utils import log as _log
from ..utils.merge_model import COMPILED_DIR as _COMPILED_DIR

from .model_paging import (verify_weights_manifest,
                           write_weights_manifest)

__all__ = ["SwapRejectedError", "export_compiled_buckets",
           "load_compiled_index", "read_compiled_blob",
           "synth_bucket_feed", "write_weights_manifest",
           "verify_weights_manifest"]

AOT_LOADS = _metrics.REGISTRY.counter(
    "paddle_deploy_aot_loads_total",
    "Serving buckets primed by deserializing an exported AOT "
    "executable (no XLA compile)")
AOT_FALLBACKS = _metrics.REGISTRY.counter(
    "paddle_deploy_aot_fallbacks_total",
    "Serving buckets that had an exported AOT executable but degraded "
    "to the compile path (digest/env/device skew or corrupt blob)")
SWAP_TOTAL = _metrics.REGISTRY.counter(
    "paddle_deploy_swap_total",
    "ServingEngine.swap_weights attempts")
SWAP_ROLLED_BACK = _metrics.REGISTRY.counter(
    "paddle_deploy_swap_rolled_back_total",
    "Weight pushes that did not land: rejected by validation/canary "
    "before the flip, or auto-rolled back by the post-swap failure "
    "watch")
COLD_START_SECONDS = _metrics.REGISTRY.gauge(
    "paddle_deploy_cold_start_seconds",
    "ServingEngine construction + warmup wall time (most recent "
    "engine)")
SWAP_BLACKOUT_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_deploy_swap_blackout_seconds",
    "Longest single-replica flip-lock hold per swap/rollback (the "
    "serving blackout a weight flip costs one replica)")

_INDEX_FILE = "index.json"


class SwapRejectedError(RuntimeError):
    """A weight push was refused (artifact/signature/canary failure) or
    auto-rolled back — the engine is still serving the prior weights."""


def synth_bucket_feed(feature_specs, bucket):
    """Zero feed for one bucket from ``{name: (feature_dims, dtype)}``
    — THE feed synthesis shared by export and ``ServingEngine.warmup``
    (one implementation, so the shapes+dtypes — and therefore the
    executor cache signature and the recorded digest — can never
    drift between export time and load time). None when any feature
    dim is dynamic."""
    feed = {}
    for name, (dims, dtype) in feature_specs.items():
        if any(d < 0 for d in dims):
            return None
        feed[name] = np.zeros((bucket,) + tuple(dims), dtype)
    return feed


def _bucket_feeds(block, feed_names, buckets):
    """(bucket, feed) per synthesizable bucket of an exported program,
    via :func:`synth_bucket_feed`. Skips buckets any dynamic non-batch
    dim makes unsynthesizable; yields nothing when a feed var is
    missing from the block."""
    specs = {}
    for name in feed_names:
        var = block.var_or_none(name)
        if var is None:
            return
        specs[name] = (tuple(var.shape or ())[1:],
                       np.dtype(var.dtype))
    for b in buckets:
        feed = synth_bucket_feed(specs, b)
        if feed is not None:
            yield b, feed


def export_compiled_buckets(dirname, scope, buckets=None, place=None):
    """AOT-compile every serving bucket of the artifact at ``dirname``
    and embed the serialized executables under ``compiled/``.

    The program is re-read from the exported ``__model__`` (not the
    in-memory pruned program) so the executor cache digest recorded per
    bucket is computed over the *same* deserialized program a loading
    engine will hold — digest equality at load time then proves program
    + signature + trace-flags + environment all match. ``scope`` only
    provides parameter shapes/dtypes for lowering; the executables are
    weight-independent (weights are runtime inputs), which is what
    makes them survive a hot weight swap.

    Returns the list of buckets exported (empty when the backend can't
    serialize executables — the artifact simply ships without
    ``compiled/`` and engines compile as before)."""
    if buckets is None:
        buckets = _config.get_flag("serving_buckets")
    buckets = tuple(sorted({int(b) for b in buckets}))
    with open(os.path.join(dirname, "__model__")) as f:
        bundle = json.load(f)
    from ..core.serialization import program_from_dict
    program = program_from_dict(bundle["program"])
    feed_names = bundle["spec"]["feed_names"]
    fetch_names = bundle["spec"]["fetch_names"]

    exe = Executor(place=place)
    # Pin the synthesized feeds to the device the export targets (the
    # place's device, default device otherwise) so the executable is
    # compiled FOR the device id the index records — a loading replica
    # on a different device is then correctly gated into the compile
    # fallback by _prime_bucket.
    try:
        dev = place.jax_device() if place is not None \
            else jax.devices()[0]
    except Exception:
        dev = jax.devices()[0]
    cdir = os.path.join(dirname, _COMPILED_DIR)
    index = {"env": _cc.env_fingerprint(),
             "device_id": dev.id,
             "feed_names": list(feed_names),
             "fetch_names": list(fetch_names),
             "buckets": {}}
    exported = []
    for b, feed in _bucket_feeds(program.global_block(), feed_names,
                                 buckets):
        feed = {n: jax.device_put(a, dev) for n, a in feed.items()}
        try:
            lowered = exe.lower(program, feed=feed,
                                fetch_list=fetch_names, scope=scope,
                                donate_state=True)
            blob = _cc.serialize_compiled(lowered.compile())
        except Exception as e:
            # backend without executable serialization (or a lowering
            # this backend refuses to serialize): ship a plain artifact
            _log.structured("aot_export_skipped", bucket=b,
                            error=repr(e))
            continue
        digest = exe.cache_digest(program, feed=feed,
                                  fetch_list=fetch_names, scope=scope)
        os.makedirs(cdir, exist_ok=True)
        fname = "bucket_%d.bin" % b
        with open(os.path.join(cdir, fname), "wb") as f:
            f.write(blob)
        index["buckets"][str(b)] = {
            "file": fname,
            "sha256": _cc.sha256_bytes(blob),
            "digest": digest,
            "nbytes": len(blob),
        }
        exported.append(b)
    if exported:
        with open(os.path.join(cdir, _INDEX_FILE), "w") as f:
            json.dump(index, f)
        _log.structured("aot_export", dir=dirname, buckets=exported)
    return exported


def load_compiled_index(model_dir):
    """The ``compiled/index.json`` dict of an artifact dir, or None
    (plain artifact, merged file already unpacked elsewhere, torn
    index). Never raises."""
    if not os.path.isdir(model_dir):
        return None
    path = os.path.join(model_dir, _COMPILED_DIR, _INDEX_FILE)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_compiled_blob(model_dir, entry):
    """One bucket blob, digest-verified against its index entry.
    Returns bytes or raises ValueError (callers fall back to compile)."""
    fname = os.path.basename(str(entry.get("file", "")))
    path = os.path.join(model_dir, _COMPILED_DIR, fname)
    with open(path, "rb") as f:
        blob = f.read()
    if _cc.sha256_bytes(blob) != entry.get("sha256"):
        raise ValueError("AOT blob %s failed digest verification"
                         % fname)
    return blob
