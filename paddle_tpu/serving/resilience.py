"""Serving-resilience primitives: errors, replica circuit breakers,
and the half-open probe loop.

PR 3 gave *training* a designed recovery path; this module extends the
same discipline to the serving tier (the component the ROADMAP north
star says must "serve heavy traffic from millions of users"), where
the failure shapes are different:

* a request whose **deadline** has already passed is doomed work — it
  must be dropped before it occupies a device, not after
  (:class:`ServingDeadlineError`);
* a **wedged or failing replica** must be quarantined out of dispatch
  instead of poisoning round-robin forever
  (:class:`ReplicaBreaker`: closed -> open on N consecutive failures
  or a single hang, -> half_open after a cooldown, -> closed when a
  probe execution succeeds — :class:`BreakerProbe` re-runs a warmed
  bucket in the background);
* **overload** should shed early, while the deadline budget can still
  be honoured elsewhere, rather than time every caller out at the
  worst moment (:class:`ServingOverloadError` — raised by the
  batcher's queue-wait EWMA admission check).

Everything here is always-on metric-wise (recovery you can't see is
recovery you can't trust — the PR-3 rule): transitions, failovers,
sheds and deadline kills flow through the observability registry
unconditionally; the *mechanisms* are armed per engine/request, so the
default healthy path stays one flag check per request.
"""

import threading
import time

from ..observability import flight as _flight
from ..observability import metrics as _metrics
from ..observability import request_trace as _rtrace
from ..utils import log as _log

__all__ = ["ServingDeadlineError", "ServingTimeoutError",
           "ServingUnavailableError", "ReplicaBreaker", "BreakerProbe",
           "run_bounded"]

DEADLINE_EXCEEDED = _metrics.REGISTRY.counter(
    "paddle_serving_deadline_exceeded_total",
    "Requests resolved with ServingDeadlineError (dropped before or "
    "rejected at dispatch)")
SHED = _metrics.REGISTRY.counter(
    "paddle_serving_shed_total",
    "Requests shed at admission (projected queue wait exceeded the "
    "deadline budget, or injected overload)")
TENANT_SHED = _metrics.REGISTRY.counter(
    "paddle_serving_tenant_shed_total",
    "Requests shed at admission attributed to one tenant (quota "
    "rejections at the fleet router, plus worker-side sheds of "
    "tenant-tagged requests) — the per-tenant slice of "
    "paddle_serving_shed_total", labelnames=("tenant",))
FAILOVER = _metrics.REGISTRY.counter(
    "paddle_serving_failover_total",
    "Requests re-dispatched to another replica after an execution "
    "failure or hang")
BREAKER_TRANSITIONS = _metrics.REGISTRY.counter(
    "paddle_serving_breaker_transitions_total",
    "Replica circuit-breaker state entries", labelnames=("state",))
REPLICA_HEALTHY = _metrics.REGISTRY.gauge(
    "paddle_serving_replica_healthy",
    "1 while the replica's breaker is closed (in dispatch rotation)",
    labelnames=("replica",))


class ServingDeadlineError(RuntimeError):
    """The request's absolute deadline passed before it was served."""


class ServingTimeoutError(RuntimeError):
    """A replica execution exceeded the per-call timeout (hang)."""


class ServingUnavailableError(RuntimeError):
    """Every replica's breaker is open — nothing healthy to dispatch to."""


def run_bounded(fn, timeout, name="serving-exec"):
    """Run ``fn()`` on a daemon worker thread bounded by ``timeout``
    seconds — the one structure that survives a wedged device call: a
    hung execution can't be cancelled, so on timeout the worker is
    left to finish (or hang forever) on its own thread and the caller
    gets :class:`ServingTimeoutError` immediately. The error carries
    the worker's done-``Event`` as ``.pending`` so the caller can cap
    leaked threads to one per quarantined unit (engine replicas track
    it as ``rep.stuck``, the generation dispatcher as a wedged-session
    marker) instead of stacking a fresh blocked thread behind every
    retry. Thread spawn cost is ~e-5 s against ms-scale executions
    (measured within noise, PROFILE.md round 9).

    On a non-timeout path the worker's return value is returned and
    its exception re-raised unchanged."""
    result = {}
    done = threading.Event()

    def work():
        try:
            result["value"] = fn()
        except BaseException as exc:  # re-raised on the caller
            result["exc"] = exc
        finally:
            done.set()

    worker = threading.Thread(target=work, daemon=True, name=name)
    worker.start()
    if not done.wait(timeout):
        err = ServingTimeoutError(
            "%s exceeded the %.3fs execution timeout" % (name, timeout))
        err.pending = done
        raise err
    if "exc" in result:
        raise result["exc"]
    return result["value"]


class ReplicaBreaker:
    """Per-replica circuit breaker.

    ``closed`` (healthy, in rotation) -> ``open`` after ``threshold``
    CONSECUTIVE execution failures, or immediately on a single hang
    past the execution timeout (a wedged device is not worth N more
    co-batched victims). ``open`` -> ``half_open`` once ``cooldown``
    seconds have passed (via :meth:`to_half_open`, driven by the
    background :class:`BreakerProbe` or by a trial dispatch when no
    replica is healthy). ``half_open`` -> ``closed`` on the next
    success, back to ``open`` on the next failure (cooldown restarts).

    A success in any state resets the consecutive-failure count: the
    threshold distinguishes a dying replica from isolated glitches,
    exactly like the trainer's ``nonfinite_budget``.
    """

    __slots__ = ("index", "threshold", "cooldown", "state", "failures",
                 "opened_at", "label", "retired", "_lock")

    def __init__(self, index, threshold, cooldown_sec, label=None):
        self.index = int(index)
        self.threshold = max(1, int(threshold))
        self.cooldown = float(cooldown_sec)
        self.state = "closed"
        self.failures = 0
        self.opened_at = 0.0
        self.retired = False  # engine closed: stop touching the gauge
        # gauge label: engines pass "engineN:replicaI" so two
        # breaker-armed engines in one process don't overwrite each
        # other's health state on the shared registry
        self.label = str(index) if label is None else str(label)
        self._lock = threading.Lock()
        REPLICA_HEALTHY.labels(replica=self.label).set(1)

    def _transition(self, new_state):
        self.state = new_state
        BREAKER_TRANSITIONS.labels(state=new_state).inc()
        if not self.retired:
            # a straggler (disowned probe attempt, in-flight run)
            # finishing after engine.close() must not resurrect the
            # gauge child close() just removed
            REPLICA_HEALTHY.labels(replica=self.label).set(
                1 if new_state == "closed" else 0)
        _log.structured("serving_breaker", replica=self.index,
                        state=new_state, failures=self.failures)
        # a transition lands on the request being served (it caused
        # it) when tracing sampled one, always on the flight ring when
        # armed — in-memory appends, safe under the breaker lock the
        # callers hold. The flight DUMP (registry snapshot + file
        # write) is NOT: record_failure fires it after release.
        _rtrace.global_event("breakerTransition", replica=self.label,
                            state=new_state, failures=self.failures)

    def record_success(self):
        with self._lock:
            self.failures = 0
            if self.state != "closed":
                self._transition("closed")

    def record_failure(self, hang=False):
        opened = False
        with self._lock:
            self.failures += 1
            if (hang or self.state == "half_open"
                    or self.failures >= self.threshold):
                if self.state != "open":
                    self._transition("open")
                    opened = True
                self.opened_at = time.monotonic()
        if opened:
            # incident-grade: snapshot the flight ring while the
            # lead-up events are still in it. Async — outside the
            # lock AND off this thread: record_failure runs on the
            # serving/generation dispatchers, which must not stall
            # behind a registry serialize + disk write mid-incident.
            _flight.RECORDER.trigger_async("breaker_open",
                                           replica=self.label,
                                           failures=self.failures)

    def ready_to_probe(self, now=None):
        if self.state != "open":
            return False
        now = time.monotonic() if now is None else now
        return now - self.opened_at >= self.cooldown

    def to_half_open(self):
        with self._lock:
            if self.state == "open":
                self._transition("half_open")


class BreakerProbe(threading.Thread):
    """Background half-open prober: for every breaker past its cooldown,
    transition to half_open and run ``probe_fn(replica_index)`` (the
    engine re-executes a warmed bucket there); success re-admits the
    replica, failure re-opens with a fresh cooldown. Daemon, started
    lazily by the engine the first time any breaker opens."""

    def __init__(self, breakers, probe_fn, interval=None):
        super().__init__(name="serving-breaker-probe", daemon=True)
        self.breakers = breakers
        self.probe_fn = probe_fn
        if interval is None:
            # resolution scales with the cooldown being awaited (a 60 s
            # cooldown doesn't need 20 Hz polling), floored for tests
            # with millisecond cooldowns
            cooldown = min((b.cooldown for b in breakers), default=1.0)
            interval = min(1.0, max(cooldown / 8.0, 0.01))
        self.interval = interval
        self._stop_ev = threading.Event()

    def run(self):
        while not self._stop_ev.is_set():
            now = time.monotonic()
            unhealthy = False
            for breaker in self.breakers:
                if self._stop_ev.is_set():
                    return
                if breaker.state == "closed":
                    continue
                unhealthy = True
                # half_open stragglers (e.g. a trial dispatch that
                # failed without recording) are probed directly, so no
                # state can strand a replica out of rotation forever
                if breaker.state != "half_open" \
                        and not breaker.ready_to_probe(now):
                    continue
                breaker.to_half_open()
                try:
                    self.probe_fn(breaker.index)
                except Exception:
                    breaker.record_failure()
                else:
                    breaker.record_success()
            # park at a coarse tick while every breaker is healthy —
            # the thread only needs fine resolution mid-incident
            self._stop_ev.wait(self.interval if unhealthy
                               else max(self.interval, 1.0))

    def stop(self, join_timeout=2.0):
        self._stop_ev.set()
        if self.is_alive():
            self.join(join_timeout)
