"""Paged KV-cache bookkeeping: the block-pool allocator and the
content-hashed prefix index behind ``generation_paged_kv``.

The dense PR-8 layout gives every sequence a full worst-case cache row
([slots, cache_len, d_model] per layer), so a 64-token chat pins the
same HBM as a 2048-token document and concurrency is capped by the most
pessimistic bucket. The paged layout (the PagedAttention insight)
stores each layer's K/V as ONE [num_blocks, block_size, d_model] pool;
a sequence owns a host-side *block table* — the list of physical block
ids backing its logical positions — and pins only ``ceil(len /
block_size)`` blocks, so concurrency becomes "pool bytes / live
tokens".

Two host-side objects, both single-threaded by contract (the
scheduler's dispatcher thread is the only caller, like the session):

* :class:`BlockPool` — free-list + per-block refcounts. A block with
  refcount 1 is exclusively owned and writable in place; refcount > 1
  means it is shared (another sequence, or the prefix index's pin) and
  a writer must copy-on-write first. ``check_invariant`` cross-checks
  the refcounts against every live table + the index pins — a leaked
  block is a test failure, not a slow OOM.
* :class:`PrefixIndex` — RadixAttention-style prompt caching at block
  granularity: prefill output blocks are registered under a running
  content hash of their token chunks (the chain hash makes a block's
  identity include its full left context), full-block hits are shared
  read-only across sequences via pool refcounts, and a partial tail
  block is shared up to the longest common token prefix (the sharer
  copies-on-write before extending it). Registered blocks hold one
  index pin each, so prompt K/V survives ``retire()`` and the next
  admission with the same prefix re-prefills only its unshared suffix;
  under pool pressure, pin-only (no live sequence) entries are evicted
  LRU.

Metrics (always-on, the serving discipline):
``paddle_generation_prefix_hits_total`` / ``_prefix_misses_total``
(admissions with/without a shared prefix),
``_prefix_shared_tokens_total`` (prompt tokens NOT re-prefilled),
``_kv_block_cows_total`` (copy-on-write block copies),
``_kv_pool_evictions_total`` (prefix blocks reclaimed under pressure),
``_kv_blocks_in_use`` (gauge per pool).
"""

import collections
import hashlib
import itertools

import numpy as np

from ..observability import metrics as _metrics
from ..observability import request_trace as _rtrace

__all__ = ["BlockPool", "PrefixIndex", "PoolExhausted"]

PREFIX_HITS = _metrics.REGISTRY.counter(
    "paddle_generation_prefix_hits_total",
    "Admissions that reused at least one cached prefix block")
PREFIX_MISSES = _metrics.REGISTRY.counter(
    "paddle_generation_prefix_misses_total",
    "Admissions that found no cached prefix block")
PREFIX_SHARED_TOKENS = _metrics.REGISTRY.counter(
    "paddle_generation_prefix_shared_tokens_total",
    "Prompt tokens served from cached prefix blocks instead of being "
    "re-prefilled")
BLOCK_COWS = _metrics.REGISTRY.counter(
    "paddle_generation_kv_block_cows_total",
    "Copy-on-write block copies (a sequence wrote into a shared "
    "block)")
POOL_EVICTIONS = _metrics.REGISTRY.counter(
    "paddle_generation_kv_pool_evictions_total",
    "Cached prefix blocks reclaimed under pool pressure (LRU, "
    "pin-only entries)")
BLOCKS_IN_USE = _metrics.REGISTRY.gauge(
    "paddle_generation_kv_blocks_in_use",
    "Referenced blocks in one session's pool (labelled per pool — "
    "sessions side by side must not overwrite each other)",
    labelnames=("pool",))
SPEC_ROLLBACKS = _metrics.REGISTRY.counter(
    "paddle_generation_kv_spec_rollback_blocks_total",
    "Blocks returned by speculative-decoding rollbacks (window rows "
    "past the accepted draft prefix)")

_POOL_SEQ = itertools.count()


class PoolExhausted(RuntimeError):
    """No free block and nothing evictable — the pool is at live
    capacity. Admission gates on ``admit_ok`` so clients normally
    never see this; mid-decode it means the growing sequence must
    finish at its current length (retired with reason 'capacity')."""


class BlockPool:
    """Fixed-size block allocator over one session's K/V pools.

    One block id indexes the same row range of EVERY per-layer K and V
    pool (all layers write the same logical positions), so the
    allocator is per-session, not per-layer. Refcounts, not ownership
    lists: a sequence's table holds one ref per entry, the prefix
    index holds one pin per registered block, and a block returns to
    the free list exactly when its count reaches zero.
    """

    def __init__(self, num_blocks, block_size):
        if num_blocks < 1 or block_size < 1:
            raise ValueError("need num_blocks >= 1 and block_size >= 1,"
                             " got %r / %r" % (num_blocks, block_size))
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free = collections.deque(range(self.num_blocks))
        self._ref = [0] * self.num_blocks
        self._label = "p%d" % next(_POOL_SEQ)
        self._gauge = BLOCKS_IN_USE.labels(pool=self._label)
        self._gauge.set(0)

    # -- accounting ------------------------------------------------------
    def free_count(self):
        return len(self._free)

    def used_count(self):
        return self.num_blocks - len(self._free)

    def refcount(self, block):
        return self._ref[block]

    def _update_gauge(self):
        self._gauge.set(self.used_count())

    # -- lifecycle -------------------------------------------------------
    def alloc(self):
        """One fresh block with refcount 1 (the caller's)."""
        if not self._free:
            # pool pressure is a per-request fate decision (starve /
            # preempt / park) — annotate the active request's trace
            _rtrace.global_event("poolExhausted",
                                 num_blocks=self.num_blocks,
                                 block_size=self.block_size)
            raise PoolExhausted(
                "all %d blocks referenced (%d-row blocks)"
                % (self.num_blocks, self.block_size))
        block = self._free.popleft()
        self._ref[block] = 1
        self._update_gauge()
        return block

    def incref(self, block):
        if self._ref[block] < 1:
            raise RuntimeError("incref on free block %d" % block)
        self._ref[block] += 1

    def decref(self, block):
        """Drop one reference; returns True when the block was freed."""
        if self._ref[block] < 1:
            raise RuntimeError("decref on free block %d" % block)
        self._ref[block] -= 1
        if self._ref[block] == 0:
            self._free.append(block)
            self._update_gauge()
            return True
        return False

    def truncate_table(self, table, n_blocks):
        """Trim a host block table IN PLACE to its first ``n_blocks``
        entries, decref'ing the dropped blocks — the speculative-
        decoding rollback (and the prepare-failure undo): window rows
        past the accepted prefix return their storage to the pool.
        Returns how many blocks were dropped."""
        surplus = table[n_blocks:]
        if not surplus:
            return 0
        del table[n_blocks:]
        for block in surplus:
            self.decref(block)
        return len(surplus)

    def close(self):
        """Retire this pool's gauge child (registry label hygiene on
        session teardown, the breaker-gauge discipline)."""
        BLOCKS_IN_USE.remove(pool=self._label)

    def check_invariant(self, tables, index=None):
        """Assert the pool books balance: every block's refcount equals
        the references the live ``tables`` (iterable of block-id lists)
        plus the ``index`` pins actually hold, free blocks carry zero
        references, and free + referenced covers the whole pool.
        Raises AssertionError with the discrepancy — tests assert this
        after retire/close/failover so a leaked block is a loud
        failure, not a slow OOM."""
        want = collections.Counter()
        for table in tables:
            want.update(int(b) for b in table)
        if index is not None:
            want.update(index.pinned_blocks())
        free = set(self._free)
        assert len(free) == len(self._free), \
            "free list holds duplicates: %r" % (self._free,)
        for block in range(self.num_blocks):
            assert self._ref[block] == want[block], (
                "block %d refcount %d but %d live references "
                "(tables + index pins)"
                % (block, self._ref[block], want[block]))
            assert (self._ref[block] == 0) == (block in free), (
                "block %d refcount %d vs free-list membership %s"
                % (block, self._ref[block], block in free))
        assert len(free) + sum(1 for r in self._ref if r > 0) == \
            self.num_blocks


def _chain_digest(parent, chunk):
    """Content hash of one block-size token chunk, chained through its
    left context: the same tokens after a different prefix hash
    differently, so a block is only ever shared between sequences whose
    ENTIRE history up to that block matches."""
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(np.ascontiguousarray(chunk, dtype=np.int64).tobytes())
    return h.digest()


class PrefixIndex:
    """Block-granular prompt cache over one :class:`BlockPool`.

    * ``match(tokens)`` — longest cached prefix: full-chunk chain-hash
      hits first, then the registered partial tail with the longest
      common token prefix. Returns ``(n_tokens, [block ids])`` without
      taking references (the admitting caller increfs what it keeps).
    * ``register(tokens, table)`` — after a prefill wrote the blocks,
      publish every full chunk (and the partial tail) of ``tokens``;
      newly registered blocks get one index pin (incref) so they
      outlive the sequence.
    * ``evict_one()`` — reclaim the LRU entry whose block no live
      sequence references (refcount == the pin alone); the allocator
      calls this under pressure before giving up.
    """

    def __init__(self, pool):
        self.pool = pool
        self.block_size = pool.block_size
        self._full = {}        # chain digest -> block id
        self._tails = {}       # chain digest -> {token tuple: block id}
        # LRU over every registered entry: key -> ("full", digest) or
        # ("tail", digest, tokens); move_to_end on every hit
        self._lru = collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.shared_tokens = 0

    def __len__(self):
        return len(self._lru)

    def pinned_blocks(self):
        """Every block currently holding an index pin (one count per
        registered entry) — the invariant checker's view."""
        out = [b for b in self._full.values()]
        for tails in self._tails.values():
            out.extend(tails.values())
        return out

    def _touch(self, key):
        self._lru[key] = True
        self._lru.move_to_end(key)

    # -- lookup ----------------------------------------------------------
    def _walk(self, tokens, touch):
        """Longest cached prefix walk -> (n_matched, blocks). With
        ``touch`` the hit entries refresh their LRU position; without,
        the walk is completely side-effect-free (the placement probe's
        contract — a capacity poll must not rewrite eviction order)."""
        tokens = np.asarray(tokens, np.int64).reshape(-1)
        bs = self.block_size
        digest = b""
        blocks = []
        i = 0
        while (i + 1) * bs <= tokens.size:
            nxt = _chain_digest(digest, tokens[i * bs:(i + 1) * bs])
            block = self._full.get(nxt)
            if block is None:
                break
            digest = nxt
            blocks.append(block)
            if touch:
                self._touch(("full", nxt))
            i += 1
        matched = i * bs
        # partial tail: longest common token prefix with any tail
        # registered under this chain position (>= 1 token shares the
        # block's leading rows; the sharer copies-on-write before
        # writing past them)
        rest = tuple(int(t) for t in tokens[matched:matched + bs])
        best_m, best_blk, best_key = 0, None, None
        for tail, block in self._tails.get(digest, {}).items():
            m = 0
            for a, b in zip(tail, rest):
                if a != b:
                    break
                m += 1
            if m > best_m:
                best_m, best_blk = m, block
                best_key = ("tail", digest, tail)
        if best_blk is not None:
            blocks.append(best_blk)
            matched += best_m
            if touch:
                self._touch(best_key)
        return matched, blocks

    def peek(self, tokens):
        """Matched-prefix LENGTH only, with no side effects at all (no
        counters, no LRU touch): what scheduler placement consults to
        decide whether a long replay journal still fits a prompt
        bucket once its cached prefix is subtracted."""
        matched, _ = self._walk(tokens, touch=False)
        return matched

    def match(self, tokens):
        """Longest cached prefix of ``tokens`` -> (n_matched, blocks).
        The caller caps ``tokens`` (generation always re-prefills at
        least the final prompt token — logits come from hidden states,
        which are not cached). No references are taken here."""
        matched, blocks = self._walk(tokens, touch=True)
        if matched:
            self.hits += 1
            self.shared_tokens += matched
            PREFIX_HITS.inc()
            PREFIX_SHARED_TOKENS.inc(matched)
        else:
            self.misses += 1
            PREFIX_MISSES.inc()
        return matched, blocks

    # -- registration ----------------------------------------------------
    def register(self, tokens, table):
        """Publish the prompt ``tokens`` whose K/V rows live in
        ``table`` (block ids covering positions [0, len(tokens))).
        Chunks already registered are left as-is (the matching path
        shares the very blocks in ``table``); new entries pin their
        block."""
        tokens = np.asarray(tokens, np.int64).reshape(-1)
        bs = self.block_size
        digest = b""
        nfull = tokens.size // bs
        for i in range(min(nfull, len(table))):
            digest = _chain_digest(digest, tokens[i * bs:(i + 1) * bs])
            if digest not in self._full:
                self._full[digest] = table[i]
                self.pool.incref(table[i])
                self._lru[("full", digest)] = True
            self._touch(("full", digest))
        tail = tuple(int(t) for t in tokens[nfull * bs:])
        if tail and len(table) > nfull:
            tails = self._tails.setdefault(digest, {})
            if tail not in tails:
                tails[tail] = table[nfull]
                self.pool.incref(table[nfull])
                self._lru[("tail", digest, tail)] = True
            self._touch(("tail", digest, tail))

    # -- eviction --------------------------------------------------------
    def _drop(self, key):
        if key[0] == "full":
            block = self._full.pop(key[1])
        else:
            tails = self._tails[key[1]]
            block = tails.pop(key[2])
            if not tails:
                del self._tails[key[1]]
        del self._lru[key]
        self.pool.decref(block)
        return block

    def evictable_count(self):
        """Entries whose block only the index keeps alive — what
        ``admit_ok`` may count as reclaimable capacity."""
        return sum(1 for b in self.pinned_blocks()
                   if self.pool.refcount(b) == 1)

    def evict_one(self):
        """Reclaim the LRU pin-only entry; True when a block was
        freed. Entries whose block a live sequence still references
        are skipped (dropping the pin would free nothing now and
        forfeit the share)."""
        for key in list(self._lru):
            block = (self._full.get(key[1]) if key[0] == "full"
                     else self._tails.get(key[1], {}).get(key[2]))
            if block is not None and self.pool.refcount(block) == 1:
                self._drop(key)
                POOL_EVICTIONS.inc()
                _rtrace.global_event("prefixEvict", block=int(block))
                return True
        return False

    def clear(self):
        """Unpin everything (session close): every registered block
        drops its index reference."""
        for key in list(self._lru):
            self._drop(key)

    def stats(self):
        return {"hits": self.hits, "misses": self.misses,
                "shared_tokens": self.shared_tokens,
                "entries": len(self._lru)}
