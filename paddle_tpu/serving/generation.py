"""Autoregressive generation serving: on-device KV-cache sessions and
a continuous-batching scheduler.

The PR-2/5/7 serving stack is stateless — every request is one padded
batch through one compiled bucket. An LLM request is a *session*: a
prompt is prefilled once, then the model is stepped token by token
against per-sequence state (the KV cache) that must live on device
between steps. This module adds that stateful tier on top of the same
machinery:

* :class:`GenerationSession` — owns one decode batch: ``slots``
  sequences, each with a per-layer [slots, cache_len, d_model] K/V
  cache resident in a Scope as persistable variables. ``admit()`` runs
  a prompt-bucket prefill program that fills ONE slot's cache rows and
  returns the first greedy token; ``step()`` runs the single decode
  program — one token per slot, per-slot positions — so sequences at
  different depths decode together. Both programs are compiled exactly
  once per shape (the executor's compile cache sees a closed set:
  one decode entry per (slot-bucket, cache-bucket), one prefill entry
  per prompt bucket — asserted via ``Executor.compile_stats()``), and
  the caches ride the executor's donated state update: every step is
  an in-place ``dynamic_update_slice`` in HBM, never a cache copy.

* :class:`GenerationScheduler` — continuous batching:
  ``submit(prompt) -> Future`` with the MicroBatcher's admission
  discipline (bounded-queue backpressure -> ServingOverloadError,
  queue-wait EWMA shedding of hopeless deadlines, per-request
  deadlines -> ServingDeadlineError), a dispatcher thread that admits
  new sequences into free cache slots and retires finished ones
  mid-flight — slot-level, never a whole-batch flush: other sequences
  keep decoding through every admit/retire — plus the engine tier's
  recovery vocabulary: a :class:`ReplicaBreaker` per session
  quarantines a failing session out of admission (trial re-admission
  after cooldown), ``drain()`` serves everything accepted before
  stopping (the redeploy story), and ``swap_weights()`` installs new
  parameter values between decode steps (the deploy-tier hot swap,
  composed with stateful sessions: the flip lands on a step boundary,
  so no single forward pass ever mixes weight versions).

Stateful failure recovery (the zero-client-error contract the
stateless tier has had since PR 5): a session's KV cache is *derived*
state — each request's host-side ``prompt`` + ``tokens`` list is a
complete, deterministic replay journal — so a session fault does not
have to surface to clients:

* **token-replay failover** (``replay_attempts`` /
  ``generation_replay_attempts`` flag): when a session's ``step()``
  or ``admit()`` fails, its in-flight requests are re-queued
  head-of-line carrying their journal; re-admission prefills
  ``prompt ⊕ tokens-so-far`` into a healthy session (promoting to a
  larger prompt bucket when the history outgrew the original one) and
  decoding continues. Greedy decode is deterministic, so the final
  output is token-for-token identical to a fault-free run. Replays
  are bounded per request, the absolute deadline is unchanged across
  them (recovery spends the caller's budget), and a poison prompt
  charges at most one breaker across all its replays — it cannot
  black out every session (the PR-5/7 lesson).
* **session rebuild** (``rebuild_limit`` /
  ``generation_rebuild_limit`` flag): a quarantined session whose
  trial re-admissions keep failing — or that wedged past the step
  timeout — is torn down and reconstructed on a background thread:
  fresh cache variables under a fresh namespace (``spec.rebuild()``;
  a leaked wedged step finishing late scribbles only on orphaned
  names), params re-read from the scope, warmup prefill + decode, and
  an atomic swap into placement on the dispatcher thread. Bounded per
  session: quarantine becomes repair, not amputation.
* **hang-free dispatch** (``step_timeout_ms`` /
  ``generation_step_timeout_ms`` flag): each session's step is
  bounded by the serving tier's worker-thread-timeout pattern
  (``resilience.run_bounded``), so one wedged ``step()`` no longer
  freezes every session and every deadline sweep — a hang is a
  failure (requests replay elsewhere, the breaker opens instantly)
  and the wedged session sits out of placement with its stuck thread
  leaked-and-capped at one.

Nothing here is constructed by default flags: with no session built,
the serving fast path, the batcher, and the executor step are
untouched (the generation_* flags are read only inside constructors),
and with the replay/rebuild/timeout flags at their defaults the
dispatcher loop is the pre-recovery hot path — no flag reads, no
worker threads, failures resolve exceptionally as before.

Metrics (always-on, like the serving front door):
``paddle_generation_requests_total``, ``_tokens_total``,
``_prefills_total``, ``_decode_steps_total``,
``_retired_total{reason}``, ``_slot_occupancy``,
``_ttft_seconds`` (time to first token), ``_inter_token_seconds``,
``_request_seconds``; recovery: ``_failover_total``,
``_replayed_tokens_total``, ``_session_rebuilds_total``,
``_step_timeouts_total``, ``_failover_recovery_seconds``.
Shed/deadline events share the serving counters
(``paddle_serving_shed_total`` / ``_deadline_exceeded_total``).
Fault sites: ``generation_step_fail`` (persistent with
``times=None``), ``generation_admit_fail``,
``generation_session_wedge`` — all indexed by session — plus the
decode-policy sites ``decode_draft_mismatch`` (force a full-reject
speculative round) and ``decode_constraint_dead_end`` (force the
typed dead-end client error), both indexed by slot.

Decode policies (PR 17, ``serving/decoding``): a session whose spec
carries a :class:`~paddle_tpu.serving.decoding.DecodePolicy` samples
on device under counter-based keys (``decoding_key(seed, position)``
— the seed is minted per request at the front door, carried in the
replay journal, and re-fed on every replay, so SAMPLED output is as
bit-replayable as greedy), optionally speculates with a draft
session (k drafts verified in ONE paged suffix-window forward,
rejected rows rolled back via the COW block machinery), and
optionally constrains output with host-compiled additive logit
masks. All of it is construction-gated: no policy, no new feeds, no
new programs — the default dispatcher path is byte-identical.
"""

import collections
import itertools
import queue
import threading
import time
import weakref
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError

import numpy as np

from .. import config as _config
from ..core.executor import Executor
from ..core.scope import global_scope
from ..observability import flight as _flight
from ..observability import metrics as _metrics
from ..observability import request_trace as _rtrace
from ..observability import tracing as _tracing
from ..resilience import faults as _faults
from ..utils import log as _log
from . import resilience as _sres
from .batcher import ServingOverloadError, _resolve, _WAIT_ALPHA
from .decoding.policy import GREEDY_FINGERPRINT, mint_seed
from .resilience import (ReplicaBreaker, ServingDeadlineError,
                         ServingUnavailableError)

__all__ = ["GenerationSpec", "GenerationSession", "GenerationScheduler"]

_REQUESTS = _metrics.REGISTRY.counter(
    "paddle_generation_requests_total",
    "Generation requests admitted into a cache slot")
_TOKENS = _metrics.REGISTRY.counter(
    "paddle_generation_tokens_total",
    "Tokens decoded across all sequences (prefill's first token "
    "included)")
_PREFILLS = _metrics.REGISTRY.counter(
    "paddle_generation_prefills_total",
    "Prompt prefills executed, per prompt bucket",
    labelnames=("bucket",))
_STEPS = _metrics.REGISTRY.counter(
    "paddle_generation_decode_steps_total",
    "Decode steps executed (one per session step, all slots at once)")
_RETIRED = _metrics.REGISTRY.counter(
    "paddle_generation_retired_total",
    "Sequences retired from their slot", labelnames=("reason",))
_OCCUPANCY = _metrics.REGISTRY.gauge(
    "paddle_generation_slot_occupancy",
    "Active sequences / total cache slots across one scheduler's "
    "sessions (labelled per scheduler — two engines side by side "
    "must not overwrite each other)", labelnames=("scheduler",))
_TTFT_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_generation_ttft_seconds",
    "Submit -> first token latency (queue wait + prefill)")
_INTER_TOKEN_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_generation_inter_token_seconds",
    "Per-sequence latency between consecutive tokens")
_REQUEST_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_generation_request_seconds",
    "Submit -> Future resolution for completed generations")
_FAILOVERS = _metrics.REGISTRY.counter(
    "paddle_generation_failover_total",
    "Requests re-queued for token-replay after their session failed "
    "(each re-admits into a healthy session, output unchanged)")
_REPLAYED_TOKENS = _metrics.REGISTRY.counter(
    "paddle_generation_replayed_tokens_total",
    "Already-generated tokens re-prefilled by replay re-admissions")
_REBUILDS = _metrics.REGISTRY.counter(
    "paddle_generation_session_rebuilds_total",
    "Quarantined sessions torn down and reconstructed (fresh cache "
    "namespace, warmed) back into placement")
_STEP_TIMEOUTS = _metrics.REGISTRY.counter(
    "paddle_generation_step_timeouts_total",
    "Decode steps that exceeded generation_step_timeout_ms (session "
    "quarantined with its worker thread leaked-and-capped)")
_RECOVERY_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_generation_failover_recovery_seconds",
    "Session failure -> the replayed request decoding again on a "
    "healthy session (re-queue wait + replay prefill)")
_SPEC_DRAFTED = _metrics.REGISTRY.counter(
    "paddle_generation_speculative_drafted_total",
    "Draft tokens proposed by speculative-decoding rounds")
_SPEC_ACCEPTED = _metrics.REGISTRY.counter(
    "paddle_generation_speculative_accepted_total",
    "Draft tokens accepted by the target's verify pass (the ratio to "
    "_drafted_total is the speculative accept rate)")

_STOP = object()

# trial re-admission failures after quarantine before a session is
# torn down and rebuilt (when rebuild is armed): the first failed
# trial may be the tail of a transient; the second says the session
# itself is broken
_REBUILD_AFTER_TRIALS = 2

# distinguishes per-session breaker gauge labels across schedulers
_SCHED_SEQ = itertools.count()

def _scheduler_health(ref):
    """The /healthz component callable for one scheduler: healthy
    while any session can take traffic (closed/half-open breaker) or
    a rebuild is on its way back; None once the scheduler is
    garbage-collected."""
    def snapshot():
        sched = ref()
        if sched is None:
            return None
        states = sched.session_health()
        # _rebuilding belongs to the dispatcher thread and has no
        # lock; this runs on the HTTP request thread, so a concurrent
        # mutation can kill the iteration — retry rather than letting
        # health_snapshot's catch report a healthy scheduler as
        # degraded during exactly the rebuild windows /healthz exists
        # to observe
        for _ in range(4):
            try:
                rebuilding = sorted(sched._rebuilding)
                break
            except RuntimeError:
                continue
        else:
            rebuilding = []
        return {"healthy": not sched._closed and
                (any(s != "open" for s in states)
                 or bool(rebuilding)),
                "closed": sched._closed,
                "sessions": states,
                "rebuilding": rebuilding,
                "active": len(sched._active)}
    return snapshot


# scope -> set of cache-variable names already driven by a live
# session. Two sessions sharing cache names on one scope would
# silently corrupt each other's KV state (slot s of one overwrites
# rows the other's slot s attends), so construction refuses the
# collision — transformer_lm_session generates a fresh cache_ns per
# call, making a second spec the correct way to add a replica.
_CACHE_CLAIMS = weakref.WeakKeyDictionary()


class GenerationSpec:
    """The contract between a model's session builder (e.g.
    ``models.transformer.transformer_lm_session``) and the generic
    session/scheduler: programs plus the feed/fetch naming.

    * ``prefill_programs``: {prompt_bucket P: Program} — tokens
      [1, P] -> first greedy token [1], writing cache slot rows [0, P).
      ``prefill_feeds`` names (tokens, prompt_len, last_pos, slot).
    * ``decode_program``: one step for ALL slots — tokens [slots, 1] +
      positions [slots] -> next token per slot. ``decode_feeds`` names
      (tokens, positions).
    * ``cache_vars``: ((name, shape, dtype), ...) persistable cache
      variables a session materializes as device zeros in its scope.
    * ``rebuild`` (optional): zero-arg factory returning an equivalent
      fresh spec under a NEW cache namespace — what session rebuild
      constructs the replacement from. A fresh namespace is
      load-bearing, not cosmetic: a wedged step leaked on its worker
      thread may finish long after the rebuild and republish the OLD
      cache names into the scope; under a new namespace those writes
      land on orphaned variables, never on the replacement's state.

    Paged mode (``paged=True``): ``cache_vars`` are
    [num_blocks, block_size, d_model] block POOLS, the programs carry
    block-table feeds (``prefill_feeds`` = (tokens, len, last_pos,
    hist, pos_idx, table); ``decode_feeds`` = (tokens, positions,
    tables)), ``copy_program``/``copy_feeds`` name the copy-on-write
    block-copy program, ``max_blocks`` is the per-sequence table
    width (ceil(cache_len / block_size)), and ``prefix_cache`` arms
    the content-hashed prompt-block index (serving/paged_cache.py).
    """

    __slots__ = ("slots", "cache_len", "max_len", "prompt_buckets",
                 "bos_id", "eos_id", "cache_vars", "prefill_programs",
                 "prefill_feeds", "prefill_fetch", "decode_program",
                 "decode_feeds", "decode_fetch", "rebuild", "paged",
                 "block_size", "num_blocks", "max_blocks",
                 "prefix_cache", "copy_program", "copy_feeds",
                 "vocab_size", "policy", "verify_program",
                 "verify_feeds", "verify_fetch", "draft_spec")

    def __init__(self, **kwargs):
        kwargs.setdefault("rebuild", None)
        kwargs.setdefault("paged", False)
        kwargs.setdefault("block_size", 0)
        kwargs.setdefault("num_blocks", 0)
        kwargs.setdefault("max_blocks", 0)
        kwargs.setdefault("prefix_cache", False)
        kwargs.setdefault("copy_program", None)
        kwargs.setdefault("copy_feeds", None)
        # decode-policy surface (serving/decoding): all None/0 when
        # the decode_* flags sit at their defaults, so every PR-8..16
        # spec construction and pickle stays valid unchanged
        kwargs.setdefault("vocab_size", 0)
        kwargs.setdefault("policy", None)
        kwargs.setdefault("verify_program", None)
        kwargs.setdefault("verify_feeds", None)
        kwargs.setdefault("verify_fetch", None)
        kwargs.setdefault("draft_spec", None)
        for name in self.__slots__:
            setattr(self, name, kwargs.pop(name))
        if kwargs:
            raise TypeError("unknown GenerationSpec fields: %s"
                            % sorted(kwargs))


class GenerationSession:
    """One decode batch: ``spec.slots`` cache slots over one scope.

    Parameters are read from ``scope`` by name (run/load them first —
    a scope trained by the standard program, or a checkpoint/artifact
    restore); cache variables are created here as device zeros. All
    methods are single-threaded by contract: the scheduler's
    dispatcher thread is the only caller in the serving deployment.

    The executor compile cache stays CLOSED over a session's lifetime:
    every ``step()`` has the same (program, feed-signature) key, every
    ``admit()`` one key per prompt bucket — ``compile_stats()`` is the
    proof, asserted in tests and printed by tools/generate_probe.py.
    """

    def __init__(self, spec, scope=None, place=None, draft_scope=None,
                 arm_quant=None):
        import jax.numpy as jnp
        self.spec = spec
        self.scope = scope if scope is not None else global_scope()
        self.place = place  # kept so a rebuild lands on the same device
        self.exe = Executor(place=place)
        # -- int8 quantized compute (serving/quant.py) -----------------
        # construction-time flag read; arming quantizes the scope's
        # weights in place and tags the programs — idempotent across
        # _rebuild (weights already int8 + scale sidecars present).
        # A shared-scope draft's programs MUST join the same arm call
        # (one scope, one selection), so the nested draft constructor
        # is told not to re-arm; a separate-scope draft arms itself.
        if arm_quant is None:
            arm_quant = bool(_config.get_flag("serving_quant_compute"))
        self._quant_armed = []
        if arm_quant:
            from . import quant as _quant
            progs = list(spec.prefill_programs.values())
            progs.append(spec.decode_program)
            if getattr(spec, "verify_program", None) is not None:
                progs.append(spec.verify_program)
            dspec = getattr(spec, "draft_spec", None)
            shared_draft = dspec is not None and draft_scope is None
            if shared_draft:
                progs += list(dspec.prefill_programs.values())
                progs.append(dspec.decode_program)
            self._quant_armed = _quant.arm_quant_compute(
                progs, self.scope)
        names = {name for name, _, _ in spec.cache_vars}
        claimed = _CACHE_CLAIMS.setdefault(self.scope, set())
        overlap = sorted(claimed & names)
        if overlap:
            raise ValueError(
                "cache variables %s on this scope are already driven "
                "by another GenerationSession — build a fresh spec "
                "(transformer_lm_session generates a unique cache_ns "
                "per call), or close() the old session" % overlap)
        claimed |= names
        self._claimed = names
        for name, shape, dtype in spec.cache_vars:
            if not self.scope.has_var(name):
                self.scope.set_var(name, jnp.zeros(shape, dtype))
        n = spec.slots
        self.lengths = np.zeros(n, np.int64)     # cached rows per slot
        self.last_token = np.zeros(n, np.int64)  # next token to decode
        self.active = np.zeros(n, bool)
        # the deepest position any sequence may WRITE: bounded by the
        # cache bucket and by the learned position table
        self.max_pos = min(spec.cache_len, spec.max_len)
        # -- paged block-pool state (spec.paged; serving/paged_cache) --
        self.paged = bool(getattr(spec, "paged", False))
        self.pool = None
        self.prefix = None
        if self.paged:
            from .paged_cache import BlockPool, PrefixIndex
            self.pool = BlockPool(spec.num_blocks, spec.block_size)
            if spec.prefix_cache:
                self.prefix = PrefixIndex(self.pool)
            # host-side block table per slot: physical block ids
            # backing logical rows [0, lengths[slot])
            self.tables = [[] for _ in range(n)]
            # slots whose next write found no allocatable block this
            # step — excluded from step() results; the scheduler (or
            # generate()) finishes them at their current length
            self._starved = set()
            # (bucket, hist, window_len) per prefill — the probe/test
            # surface proving a shared prefix was NOT re-prefilled;
            # bounded (see _admit_paged) so a long-lived session
            # doesn't accumulate host memory per admission
            self.prefill_log = []
        # -- decode-policy state (spec.policy; serving/decoding) -------
        policy = getattr(spec, "policy", None)
        self.policy = policy
        self.sampled = policy is not None and policy.sampled
        self.constrained = policy is not None and \
            policy.constraint is not None
        self.speculative = policy is not None and policy.speculate_k > 0
        # per-slot request seed / constraint-automaton state, set at
        # admission, journal-recomputable (the replay contract)
        self.seeds = np.zeros(n, np.int64)
        self.cstate = [None] * n
        self._mask_table = None
        if self.constrained:
            self._mask_table = policy.constraint.mask_table(
                spec.vocab_size)
        self.draft = None
        if self.speculative:
            # the draft mirrors the target slot-for-slot: admitted,
            # advanced, and retired in lockstep. Default drafts share
            # the target's scope (parameter-name truncation = free
            # self-draft); dim-changed drafts need their own scope.
            self.draft = GenerationSession(
                spec.draft_spec,
                scope=self.scope if draft_scope is None else draft_scope,
                place=place,
                arm_quant=False if draft_scope is None else None)

    # -- slot bookkeeping ------------------------------------------------
    def free_slots(self):
        return [int(i) for i in np.flatnonzero(~self.active)]

    def active_slots(self):
        return [int(i) for i in np.flatnonzero(self.active)]

    def occupancy(self):
        return float(self.active.sum()) / self.spec.slots

    def capacity_left(self, slot):
        """Decode steps slot can still take before its cache bucket or
        position table runs out."""
        return int(self.max_pos - self.lengths[slot])

    def prompt_bucket(self, n):
        for p in self.spec.prompt_buckets:
            if n <= p:
                return p
        return None

    def compile_stats(self):
        return self.exe.compile_stats()

    # -- paged-pool surface (no-ops / trivial on the dense layout) -------
    def admit_ok(self, n_tokens):
        """Can an ``n_tokens``-history admission get storage RIGHT NOW?
        Dense: always (storage is the slot itself — ``free_slots`` is
        the gate). Paged: enough free-or-evictable blocks to cover the
        whole history PLUS one copy-on-write block when the prefix
        cache is armed. The accounting is sharing-independent: if the
        admission matches m cached blocks it needs m fewer fresh ones
        but also pins those m previously-evictable entries, so the two
        cancel and ``free + evictable >= ceil(n/bs) + cow_margin`` is
        the right test without knowing the tokens. The scheduler
        consults this during placement so pool pressure parks a
        request instead of turning into an admit exception that would
        charge a healthy session's breaker."""
        if not self.paged:
            return True
        need = -(-min(int(n_tokens), self.max_pos)
                 // self.spec.block_size)
        avail = self.pool.free_count()
        if self.prefix is not None:
            # a matched prefix ending mid-block copies-on-write one
            # extra block during the admission itself — but never
            # demand more than the pool HAS: a history that needs
            # exactly the whole pool can only need the COW block when
            # something matched, in which case the match freed that
            # many fresh allocations; capping keeps such a request
            # admittable instead of parked forever
            need = min(need + 1, self.pool.num_blocks)
            if avail < need:
                avail += self.prefix.evictable_count()
        return avail >= need

    def storable(self, n_tokens):
        """Static bound: could this session's storage EVER hold an
        ``n_tokens`` history? Dense storage is the slot row itself
        (``max_pos`` covers it); a paged pool must have enough blocks
        IN TOTAL — placement must not park a request forever on a
        pool that can never satisfy it, however much retires free."""
        if not self.paged:
            return True
        return -(-int(n_tokens) // self.spec.block_size) <= \
            self.pool.num_blocks

    def window_fits(self, history):
        """Placement probe for a history whose FULL length fits no
        prompt bucket: with the prefix cache armed, the cached prefix
        shrinks the window that actually needs one — a PR-9 replay
        journal that outgrew every bucket is still admissible here
        when its prompt prefix is cached, so failover composes with
        prefix reuse instead of dying on bucket promotion. Entirely
        side-effect-free (``PrefixIndex.peek``); dense sessions and
        prefix-off pools return False, preserving the old verdict."""
        if not self.paged or self.prefix is None:
            return False
        history = np.asarray(history, np.int64).reshape(-1)
        n = history.size
        if n < 1 or n > self.max_pos:
            return False
        matched = self.prefix.peek(history[:n - 1])
        return self.prompt_bucket(n - matched) is not None

    def pool_stats(self):
        """{blocks_in_use, num_blocks, block_size, bytes_per_block}
        for the paged layout (None on dense) — probe/bench surface."""
        if not self.paged:
            return None
        itemsize = np.dtype(self.spec.cache_vars[0][2]).itemsize
        d_model = self.spec.cache_vars[0][1][2]
        return {"blocks_in_use": self.pool.used_count(),
                "num_blocks": self.pool.num_blocks,
                "block_size": self.spec.block_size,
                "bytes_per_block": self.spec.block_size * d_model
                * itemsize * len(self.spec.cache_vars)}

    def prefix_stats(self):
        """Prefix-cache hit counters (zeros when not armed)."""
        if self.prefix is None:
            return {"hits": 0, "misses": 0, "shared_tokens": 0,
                    "entries": 0}
        return self.prefix.stats()

    def check_pool_invariant(self):
        """Assert the block-pool books balance against every live
        table and index pin (serving/paged_cache.py) — the
        pool-accounting invariant tests assert after retire / close /
        failover so a leaked block fails loudly. No-op on dense."""
        if self.paged:
            self.pool.check_invariant(
                (self.tables[s] for s in range(self.spec.slots)),
                self.prefix)

    def _alloc_block(self):
        """One fresh block, reclaiming cold prefix-cache entries under
        pressure (LRU, pin-only) before giving up."""
        from .paged_cache import PoolExhausted
        while True:
            try:
                return self.pool.alloc()
            except PoolExhausted:
                if self.prefix is None or not self.prefix.evict_one():
                    raise

    def _release_table(self, slot):
        for block in self.tables[slot]:
            self.pool.decref(block)
        self.tables[slot] = []

    def _copy_block(self, src, dst):
        """Run the block-copy program: block ``src`` -> ``dst`` in
        every layer's K and V pool (device-side, in place under
        donation — COW never round-trips the cache through the
        host)."""
        f_src, f_dst = self.spec.copy_feeds
        self.exe.run(self.spec.copy_program,
                     feed={f_src: np.asarray([src], np.int32),
                           f_dst: np.asarray([dst], np.int32)},
                     fetch_list=[], scope=self.scope)

    def _ensure_writable(self, table, idx):
        """Copy-on-write: if ``table[idx]`` is shared (another
        sequence's table or a prefix-index pin also holds it), copy it
        into a fresh block and swap that into the table — the writer
        diverges onto its own copy, sharers keep the original
        untouched. Raises PoolExhausted when no block is allocatable."""
        from .paged_cache import BLOCK_COWS
        old = table[idx]
        if self.pool.refcount(old) <= 1:
            return
        new = self._alloc_block()
        try:
            self._copy_block(old, new)
        except BaseException:
            self.pool.decref(new)
            raise
        self.pool.decref(old)
        table[idx] = new
        BLOCK_COWS.inc()
        # lands on the admitting request's trace (admit-path COW runs
        # under its activated context); step_prepare COWs have no
        # single owner and reach only the flight ring
        _rtrace.global_event("blockCOW", src=int(old), dst=int(new))

    def close(self):
        """Release this session's cache-variable claim (and drop the
        cache arrays from the scope), so a later session may reuse the
        names. Paged: every block reference — slot tables AND prefix
        pins — is returned to the pool first, and the accounting
        invariant is re-checked so a teardown (including the PR-9
        rebuild path, which closes the old session on hand-over) can
        never leak a block. Idempotent; the session must not be
        stepped after."""
        if self.draft is not None:
            self.draft.close()
            self.draft = None
        if self.paged and self.pool is not None:
            for slot in range(self.spec.slots):
                self._release_table(slot)
            if self.prefix is not None:
                self.prefix.clear()
            self.check_pool_invariant()
            assert self.pool.used_count() == 0, \
                "closed session leaked %d blocks" % self.pool.used_count()
            self.pool.close()
            self.pool = None
            self.prefix = None
            self.paged = False
        claimed = _CACHE_CLAIMS.get(self.scope)
        if claimed is not None:
            claimed -= self._claimed
        for name in self._claimed:
            self.scope.erase(name)
        self._claimed = set()
        self.active[:] = False

    # -- decode-policy plumbing ------------------------------------------
    def _policy_prefill_feed(self, feed, n, seed, cstate):
        """Append the decode-policy feeds to a prefill feed dict.
        ``n`` is the TOTAL history length — the sequence index of the
        token this prefill emits, i.e. the counter in decoding_key —
        so a replay prefilling prompt+journal lands on the exact key
        the original decode used at that position."""
        if self.sampled:
            feed["gen.pseed"] = np.asarray([seed], np.int64)
            feed["gen.pstep"] = np.asarray([n], np.int32)
        if self.constrained:
            c = self.policy.constraint
            state = c.start if cstate is None else cstate
            feed["gen.pmask"] = self._mask_table[
                c.state_index(state)].reshape(1, -1)

    def _policy_admitted(self, slot, first, seed, cstate):
        """Record per-slot policy state once an admission emitted its
        first token, and mirror the admission into the draft."""
        self.seeds[slot] = int(seed)
        if self.constrained:
            c = self.policy.constraint
            state = c.start if cstate is None else cstate
            self.cstate[slot] = c.advance(state, int(first))

    def _draft_admit(self, prompt, slot, first):
        """Mirror an admission into the draft session (same slot by
        lockstep construction), then pin its pending token to the
        TARGET's emission — the draft guesses continuations of the
        target's trajectory, never its own."""
        if self.draft is None:
            return
        try:
            dslot, _ = self.draft.admit(prompt)
        except BaseException:
            self.retire(slot)
            raise
        if dslot != slot:
            self.retire(slot)
            raise RuntimeError(
                "draft session desynchronized: target slot %d, draft "
                "slot %d" % (slot, dslot))
        self.draft.last_token[slot] = int(first)

    # -- execution -------------------------------------------------------
    def admit(self, prompt, seed=0, cstate=None):
        """Prefill ``prompt`` (1-D int ids) into a free slot: the
        prompt's K/V rows land in the cache, the slot becomes active,
        and the first greedy token is returned as ``(slot, token)``.
        Raises RuntimeError when no slot is free and ValueError when
        the prompt fits no bucket.

        Paged layout: storage comes from the block pool through a
        fresh block table; with the prefix cache armed, the longest
        content-hash-matched prefix is SHARED (its blocks referenced,
        not recomputed) and only the unshared suffix is prefilled —
        capped at len-1, because logits need the last prompt token's
        hidden state, which only a forward pass produces."""
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        n = prompt.size
        if n < 1:
            raise ValueError("empty prompt")
        if self.paged:
            return self._admit_paged(prompt, seed, cstate)
        bucket = self.prompt_bucket(n)
        if bucket is None:
            raise ValueError(
                "prompt length %d exceeds the largest prompt bucket %d"
                % (n, self.spec.prompt_buckets[-1]))
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free cache slot (%d active)"
                               % self.spec.slots)
        slot = free[0]
        padded = np.full((1, bucket), self.spec.eos_id, np.int64)
        padded[0, :n] = prompt
        f_tok, f_len, f_pos, f_slot = self.spec.prefill_feeds[:4]
        feed = {f_tok: padded,
                f_len: np.asarray([n], np.int32),
                f_pos: np.asarray([n - 1], np.int32),
                f_slot: np.asarray([slot], np.int32)}
        self._policy_prefill_feed(feed, n, seed, cstate)
        with _tracing.span("generationPrefill", bucket=bucket):
            outs = self.exe.run(
                self.spec.prefill_programs[bucket], feed=feed,
                fetch_list=[self.spec.prefill_fetch], scope=self.scope)
        first = int(np.asarray(outs[0]).reshape(-1)[0])
        self.lengths[slot] = n
        self.last_token[slot] = first
        self.active[slot] = True
        self._policy_admitted(slot, first, seed, cstate)
        self._draft_admit(prompt, slot, first)
        _PREFILLS.labels(bucket=bucket).inc()
        return slot, first

    def _admit_paged(self, prompt, seed=0, cstate=None):
        """Paged admission: match the cached prefix, reference its
        blocks, allocate fresh ones for the rest, prefill ONLY the
        unshared suffix window, then register the prompt's blocks in
        the prefix index. All block references taken here are rolled
        back if anything below fails — the pool can't leak on an
        admission error."""
        n = prompt.size
        bs = self.spec.block_size
        if n > self.max_pos:
            raise ValueError(
                "prompt length %d exceeds the cache capacity %d"
                % (n, self.max_pos))
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free cache slot (%d active)"
                               % self.spec.slots)
        slot = free[0]
        matched, shared = 0, []
        if self.prefix is not None:
            # cap at n-1: the final prompt token is always re-run —
            # its logits come from hidden states, which are not cached
            matched, shared = self.prefix.match(prompt[:n - 1])
        suffix = prompt[matched:]
        bucket = self.prompt_bucket(suffix.size)
        if bucket is None:
            raise ValueError(
                "prompt length %d (unshared suffix %d) exceeds the "
                "largest prompt bucket %d"
                % (n, suffix.size, self.spec.prompt_buckets[-1]))
        table = list(shared)
        for block in shared:
            self.pool.incref(block)
        try:
            if matched % bs:
                # the matched prefix ends MID-block: the suffix writes
                # into that shared block, so diverge onto a copy first
                self._ensure_writable(table, len(table) - 1)
            while len(table) * bs < n:
                table.append(self._alloc_block())
            w = suffix.size
            padded = np.full((1, bucket), self.spec.eos_id, np.int64)
            padded[0, :w] = suffix
            pix = np.clip(matched + np.arange(bucket), 0,
                          self.spec.max_len - 1).astype(np.int32)
            tab = np.full(self.spec.max_blocks, self.pool.num_blocks,
                          np.int32)
            tab[:len(table)] = table
            f_tok, f_len, f_pos, f_hist, f_pix, f_tab = \
                self.spec.prefill_feeds[:6]
            feed = {f_tok: padded,
                    f_len: np.asarray([w], np.int32),
                    f_pos: np.asarray([w - 1], np.int32),
                    f_hist: np.asarray([matched], np.int32),
                    f_pix: pix,
                    f_tab: tab}
            # the emitted token's index is the TOTAL length n
            # (= matched + w), prefix sharing included
            self._policy_prefill_feed(feed, n, seed, cstate)
            with _tracing.span("generationPrefill", bucket=bucket,
                               hist=matched):
                outs = self.exe.run(
                    self.spec.prefill_programs[bucket], feed=feed,
                    fetch_list=[self.spec.prefill_fetch],
                    scope=self.scope)
        except BaseException:
            for block in table:
                self.pool.decref(block)
            raise
        first = int(np.asarray(outs[0]).reshape(-1)[0])
        if self.prefix is not None:
            # publish the prompt's blocks (full chunks + partial
            # tail) — the next admission sharing this prefix, or a
            # PR-9 token replay of it, prefills only its suffix
            self.prefix.register(prompt, table)
        self.tables[slot] = table
        self.lengths[slot] = n
        self.last_token[slot] = first
        self.active[slot] = True
        self._policy_admitted(slot, first, seed, cstate)
        self._draft_admit(prompt, slot, first)
        self._starved.discard(slot)
        self.prefill_log.append((bucket, matched, w))
        if len(self.prefill_log) > 4096:     # keep a list (tests
            del self.prefill_log[:2048]      # slice it), bounded
        _PREFILLS.labels(bucket=bucket).inc()
        return slot, first

    def step(self):
        """One decode step for EVERY active slot: each slot's pending
        token is embedded at its own position, its K/V row appended in
        place, and its single query attended against the live cache
        prefix. Returns {slot: next_token} for active slots (free
        slots compute masked garbage that the next prefill
        overwrites). Raises RuntimeError when an active slot is out of
        cache capacity — retire it first.

        Paged layout: a slot whose next write needs a block the pool
        cannot supply (even after evicting cold prefix entries) is
        EXCLUDED from the result — it neither advances nor writes
        (its table feed row is dead, so the device write drops) and
        the caller finishes it at its current length. Dense sessions
        never exclude a slot.

        Internally two phases — :meth:`step_prepare` (ALL host-side
        pool/table mutation) then :meth:`step_run` (the device call) —
        so the scheduler's bounded-step path can keep allocator books
        off the worker thread (see step_prepare)."""
        prepared = self.step_prepare()
        if prepared is None:
            return {}
        return self.step_run(prepared)

    def step_prepare(self):
        """Phase 1 of a decode step: the active-slot snapshot, the
        capacity check, and — on the paged layout — EVERY host-side
        pool mutation (block growth, copy-on-write, the table feed)
        plus snapshotted feeds. Returns an opaque handle for
        :meth:`step_run`, or None with nothing active.

        The split is a thread-safety contract, not a convenience: the
        scheduler's step-timeout path runs the device call on a
        worker thread it may LEAK past the timeout. The dense layout
        tolerates that (a leaked step touches only device state and
        per-slot numpy scalars), but allocator refcounts would not —
        so they are only ever touched here, on the caller/dispatcher
        thread, and a wedged worker can never race retire()/close()
        on the pool books.

        One caveat: a copy-on-write divergence runs the (rare,
        per-divergence) block-copy program here too — the table swap
        is only valid once the copy succeeded, so the two cannot be
        split across threads. That device call therefore shares
        ``admit()``'s exposure, not ``step()``'s: like every prefill,
        it runs unbounded on the dispatcher (the step timeout has
        always bounded only the per-token decode call)."""
        act = np.flatnonzero(self.active)
        if act.size == 0:
            return None
        if (self.lengths[act] >= self.max_pos).any():
            over = [int(s) for s in act
                    if self.lengths[s] >= self.max_pos]
            raise RuntimeError(
                "slots %s are at cache capacity %d — retire before "
                "stepping" % (over, self.max_pos))
        if self.speculative:
            W = self.policy.speculate_k + 1
            if all(self.capacity_left(int(s)) >= W for s in act):
                return self._prepare_spec(act)
            # near capacity: a window write would overrun the cache —
            # fall back to plain single-token rounds, which finish
            # these slots (speculation resumes once they retire)
        if self.paged:
            return self._prepare_paged(act)
        f_tok, f_pos = self.spec.decode_feeds[:2]
        feed = {f_tok: self.last_token.reshape(-1, 1).copy(),
                f_pos: self.lengths.astype(np.int32)}
        self._policy_decode_feed(feed)
        return (act, frozenset(), feed)

    def _policy_decode_feed(self, feed):
        """Append the decode-policy feeds to a decode-step feed dict.
        Step = lengths + 1: a slot at length L emits the token at
        sequence index L+1 — its decoding_key counter."""
        if self.sampled:
            feed["gen.dseed"] = self.seeds.copy()
            feed["gen.dstep"] = (self.lengths + 1).astype(np.int32)
        if self.constrained:
            c = self.policy.constraint
            mask = np.zeros((self.spec.slots, self.spec.vocab_size),
                            np.float32)
            for s in np.flatnonzero(self.active):
                state = self.cstate[int(s)]
                if state is not None:
                    mask[int(s)] = self._mask_table[c.state_index(state)]
            feed["gen.dmask"] = mask

    def _prepare_paged(self, act):
        """Paged phase 1: grow/copy-on-write each active slot's write
        block and build the table feed. Inactive and pool-starved
        slots get all-dead table rows, so their device writes DROP —
        a slot can never scribble on blocks it does not own."""
        from .paged_cache import PoolExhausted
        bs = self.spec.block_size
        self._starved.clear()   # a retire may have freed blocks since
        for s in act:
            s = int(s)
            pos = int(self.lengths[s])
            tbl = self.tables[s]
            try:
                if pos // bs == len(tbl):
                    tbl.append(self._alloc_block())
                else:
                    # writing into a block a sharer or the prefix
                    # index also holds: diverge onto a private copy
                    self._ensure_writable(tbl, pos // bs)
            except PoolExhausted:
                self._starved.add(s)
        nb = self.pool.num_blocks
        tab = np.full((self.spec.slots, self.spec.max_blocks), nb,
                      np.int32)
        for s in act:
            s = int(s)
            if s in self._starved:
                continue
            tbl = self.tables[s]
            tab[s, :len(tbl)] = tbl
        f_tok, f_pos, f_tab = self.spec.decode_feeds[:3]
        feed = {f_tok: self.last_token.reshape(-1, 1).copy(),
                f_pos: self.lengths.astype(np.int32),
                f_tab: tab}
        self._policy_decode_feed(feed)
        return (act, frozenset(self._starved), feed)

    def _prepare_spec(self, act):
        """Speculative phase 1: extend each active slot's block table
        to cover the verify-window rows [L, L+W) — block growth and
        copy-on-write only, on the dispatcher thread (step_prepare's
        allocator contract). A slot the pool cannot cover is starved
        out of the round exactly like plain paged starvation, its
        this-round growth returned."""
        from .paged_cache import PoolExhausted
        bs = self.spec.block_size
        W = self.policy.speculate_k + 1
        self._starved.clear()
        info = {}
        for s in act:
            s = int(s)
            L = int(self.lengths[s])
            tbl = self.tables[s]
            held = len(tbl)
            need = (L + W - 1) // bs + 1
            try:
                for bi in range(L // bs, min(held, need)):
                    self._ensure_writable(tbl, bi)
                while len(tbl) < need:
                    tbl.append(self._alloc_block())
            except PoolExhausted:
                self.pool.truncate_table(tbl, held)
                self._starved.add(s)
                continue
            tab = np.full(self.spec.max_blocks, self.pool.num_blocks,
                          np.int32)
            tab[:len(tbl)] = tbl
            info[s] = (L, tab)
        return {"slots": info, "starved": frozenset(self._starved)}

    def step_run(self, prepared):
        """Phase 2 of a decode step: the device call plus result
        application. Touches no allocator state — safe to execute on
        the scheduler's bounded (leakable) worker thread; the feeds
        and starved-set were snapshotted at prepare time. (The
        speculative round is the one exception: it runs drafting,
        verification AND pool rollback here, which is why the
        scheduler refuses step_timeout_ms on speculative sessions —
        that round only ever executes inline on the dispatcher.)"""
        if isinstance(prepared, dict):
            return self._step_run_spec(prepared)
        act, starved, feed = prepared
        with _tracing.span("generationStep",
                           active=int(act.size)):
            outs = self.exe.run(
                self.spec.decode_program, feed=feed,
                fetch_list=[self.spec.decode_fetch], scope=self.scope)
        nxt = np.asarray(outs[0]).reshape(-1)
        result = {}
        for s in act:
            s = int(s)
            if s in starved:
                continue
            self.lengths[s] += 1
            self.last_token[s] = int(nxt[s])
            result[s] = int(nxt[s])
            if self.constrained:
                self.cstate[s] = self.policy.constraint.advance(
                    self.cstate[s], int(nxt[s]))
        if self.draft is not None and result:
            self._draft_mirror_plain(result)
        return result

    def _draft_mirror_plain(self, result):
        """A plain single-token round under a speculative session (the
        near-capacity fallback): the draft must still append the
        pending token's K/V row to stay coherent, so step it once —
        its own emission is discarded — and pin its pending token to
        the target's."""
        self.draft.step()
        for s in self.draft.active_slots():
            if s in result:
                self.draft.last_token[s] = result[s]
            else:
                # target starved this slot while the draft advanced:
                # mirror the target's (unchanged) state back
                self.draft.lengths[s] = int(self.lengths[s])
                self.draft.last_token[s] = int(self.last_token[s])

    def _step_run_spec(self, prepared):
        """Speculative phase 2: k+1 batched greedy draft steps, then
        per-slot one-pass verification against the TARGET's policy,
        multi-token application, and block rollback. Returns
        {slot: [token, ...]} — each list is the accepted draft prefix
        plus the target's correction/bonus token, so it is exactly
        the tokens plain rounds would have emitted one at a time."""
        from .paged_cache import SPEC_ROLLBACKS
        info = prepared["slots"]
        starved = prepared["starved"]
        k = self.policy.speculate_k
        W = k + 1
        bs = self.spec.block_size
        # snapshot draft pendings: starved slots sit the round out on
        # the target but the batched draft advances them anyway
        restore = {s: (int(self.draft.lengths[s]),
                       int(self.draft.last_token[s]))
                   for s in starved}
        # phase A: k proposals per slot, plus one extra step so the
        # draft's cache holds a K/V row for EVERY window position a
        # full acceptance confirms (the bonus-token row)
        drafts = {s: [] for s in info}
        for i in range(W):
            out = self.draft.step()
            if i < k:
                for s in drafts:
                    drafts[s].append(out[s])
        # phase B: one suffix-window forward per speculating slot
        vtok, vlen, vhist, vpix, vtab, vseed = self.spec.verify_feeds
        result = {}
        for s, (L, tab) in sorted(info.items()):
            window = np.empty((1, W), np.int64)
            window[0, 0] = self.last_token[s]
            window[0, 1:] = drafts[s]
            pix = np.clip(L + np.arange(W), 0,
                          self.spec.max_len - 1).astype(np.int32)
            with _tracing.span("generationVerify", window=W):
                outs = self.exe.run(
                    self.spec.verify_program,
                    feed={vtok: window,
                          vlen: np.asarray([W], np.int32),
                          vhist: np.asarray([L], np.int32),
                          vpix: pix,
                          vtab: tab,
                          vseed: np.asarray([self.seeds[s]],
                                            np.int64)},
                    fetch_list=list(self.spec.verify_fetch),
                    scope=self.scope)
            toks = np.asarray(outs[0]).reshape(-1)
            accept = int(np.asarray(outs[1]).reshape(-1)[0])
            if _faults.should_fire("decode_draft_mismatch",
                                   index=s) is not None:
                accept = 0   # chaos hook: force a full-reject round
            _SPEC_DRAFTED.inc(k)
            _SPEC_ACCEPTED.inc(accept)
            emitted = [int(t) for t in toks[:accept + 1]]
            new_len = L + accept + 1
            self.lengths[s] = new_len
            self.last_token[s] = emitted[-1]
            # roll back window rows past the confirmed prefix: blocks
            # beyond the new length decref (prepare's COW already
            # diverged every shared write block, so sharers are safe);
            # surviving garbage rows sit beyond the length mask and
            # are overwritten in place by later writes
            freed = self.pool.truncate_table(
                self.tables[s], (new_len - 1) // bs + 1)
            if freed:
                SPEC_ROLLBACKS.inc(freed)
            # draft rollback is a length truncation: its rows live at
            # fixed positions, so rejected rows are simply overwritten
            self.draft.lengths[s] = new_len
            self.draft.last_token[s] = emitted[-1]
            result[s] = emitted
        for s, (dl, dt) in restore.items():
            self.draft.lengths[s] = dl
            self.draft.last_token[s] = dt
        return result

    def retire(self, slot):
        """Free a slot mid-flight. The cache rows are left as-is — the
        next prefill into this slot overwrites them, and the per-slot
        length mask keeps them unattendable meanwhile. Paged: every
        block reference the slot's table held is returned to the pool
        (a block shared with the prefix index survives as cached
        prompt state; exclusive blocks free immediately)."""
        self.active[slot] = False
        self.lengths[slot] = 0
        self.last_token[slot] = 0
        self.seeds[slot] = 0
        self.cstate[slot] = None
        if self.draft is not None:
            self.draft.retire(slot)
        if self.paged:
            self._release_table(slot)
            self._starved.discard(slot)

    def generate(self, prompt, max_new_tokens=None, eos_id=None,
                 seed=0):
        """Synchronous single-sequence convenience (tests/probes): the
        policy continuation of ``prompt`` (greedy by default),
        stopping at ``eos_id`` or ``max_new_tokens``, as a list of ids
        (EOS excluded). ``seed`` keys sampled policies."""
        eos = self.spec.eos_id if eos_id is None else eos_id
        slot, first = self.admit(prompt, seed=seed)
        # prefill already produced one token; each further step can
        # write one more K/V row, so cap+1 tokens total fit the slot
        cap = self.capacity_left(slot)
        limit = cap + 1 if max_new_tokens is None \
            else min(int(max_new_tokens), cap + 1)
        tokens = [first]
        try:
            while tokens[-1] != eos and len(tokens) < limit:
                nxt = self.step()
                if slot not in nxt:
                    break  # paged pool exhausted: finish at length
                got = nxt[slot]
                # speculative rounds emit a LIST per slot; tokens past
                # EOS or the budget are discarded (the round could not
                # know the sequence would end mid-window)
                for t in (got if isinstance(got, list) else [got]):
                    tokens.append(t)
                    if t == eos or len(tokens) >= limit:
                        break
        finally:
            self.retire(slot)
        if tokens and tokens[-1] == eos:
            tokens = tokens[:-1]
        return tokens


class _GenRequest:
    __slots__ = ("prompt", "max_new", "explicit_budget", "eos_id",
                 "future", "deadline", "t_submit", "tokens", "slot",
                 "session_index", "t_last", "t_queued", "replays",
                 "charged", "failed_on", "last_exc", "ctx",
                 "on_token", "seed", "tenant")

    def __init__(self, prompt, max_new, explicit_budget, eos_id,
                 deadline, on_token=None, seed=0, tenant=None):
        self.prompt = prompt
        # the request's decode-RNG seed: minted ONCE at the front
        # door, re-fed on every replay admission — together with the
        # prompt+tokens journal it makes SAMPLED decode exactly as
        # replayable as greedy (serving/decoding)
        self.seed = seed
        # tenant id forwarded over the fleet envelope (None when the
        # caller is single-tenant): shed/trace attribution only — the
        # scheduler's admission math is tenant-blind, quotas live at
        # the router
        self.tenant = tenant
        self.max_new = max_new
        # True when the CALLER asked for max_new tokens (placement
        # must find a session able to serve them all); False when the
        # budget is the implicit "as much as fits" cap, which any
        # fitting session satisfies by definition
        self.explicit_budget = explicit_budget
        self.eos_id = eos_id  # None until placement picks a session
        self.future = Future()
        self.deadline = deadline  # absolute time.monotonic() or None
        self.t_submit = time.perf_counter()
        # last enqueue time: t_submit at first, reset on a replay
        # re-queue so the admission-wait EWMA keeps measuring QUEUE
        # wait, not time-since-original-submit (a replay would
        # otherwise latch the shed estimate high); the deadline keeps
        # using t_submit — replay spends the caller's budget
        self.t_queued = self.t_submit
        self.tokens = []
        self.slot = None
        self.session_index = None
        self.t_last = None
        self.replays = 0      # replay re-admissions consumed
        # True once this request's own failure charged a breaker: a
        # poison prompt failing over across sessions charges at most
        # ONE — it cannot quarantine the whole fleet
        self.charged = False
        # sessions this request has already failed on: replay
        # re-placement prefers anything else first. Without this, a
        # sub-threshold breaker (still closed after the charge) keeps
        # winning lowest-index placement and the request burns its
        # whole replay budget on the one broken session while a
        # healthy one sits idle.
        self.failed_on = set()
        # the failure that parked this request for replay: if the
        # replay turns out to be impossible (journal outgrew every
        # prompt bucket, no session ever heals), THIS surfaces — not
        # a generic unavailable error that masks what happened
        self.last_exc = None
        # request-scoped TraceContext (None = tracing off/unsampled).
        # It lives on the SAME object as the replay journal, so a
        # failover hop keeps its trace id across sessions for free —
        # the one-trace-per-request contract.
        self.ctx = None
        # optional per-token observer (the fleet tier streams tokens
        # over the wire as they decode, so a killed process's journal
        # survives on the router). Called on the dispatcher thread
        # with each NEWLY generated token — including an EOS the
        # resolution then strips (the Future's result stays
        # authoritative) and the token a replay re-admission owed;
        # never re-called for journal tokens a replay re-prefills.
        # Must not block; an observer exception is the caller's bug
        # but must not kill the dispatcher.
        self.on_token = on_token

    def notify_token(self, token):
        if self.on_token is not None:
            try:
                self.on_token(token)
            except Exception:  # noqa: BLE001 — dispatcher must live
                _log.logger().warning(
                    "generation on_token observer failed",
                    exc_info=True)

    def history(self):
        """The replay journal: prompt plus every token generated so
        far — prefilling it reconstructs the exact decode state (and
        the next prefill token IS the token the failed step owed)."""
        if not self.tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int64)])


class GenerationScheduler:
    """Continuous-batching front door over one or more
    :class:`GenerationSession` replicas.

    ``submit(prompt) -> Future`` resolves to the generated ids as an
    int64 array (greedy continuation, EOS excluded). The dispatcher
    thread interleaves two moves forever: admit queued requests into
    free cache slots (prefill), and run one decode step for every
    session with active slots. Sequences finish (EOS / token budget /
    deadline) and retire slot-by-slot — co-resident sequences never
    stall or flush for an admit or retire.

    Admission reuses the MicroBatcher discipline: bounded queue
    (``submit`` blocks, or raises :class:`ServingOverloadError` with a
    ``timeout``), queue-wait EWMA shedding when a deadline budget is
    already hopeless, expired deadlines resolved with
    :class:`ServingDeadlineError` before touching a device; a deadline
    that expires MID-generation retires the slot and resolves the
    Future with ServingDeadlineError (stateful requests hold a slot —
    letting them linger past their budget starves admission).

    With ``breaker_failures`` (default: the
    ``serving_breaker_failures`` flag; 0 = off) each session gets a
    :class:`ReplicaBreaker`: a failing session is quarantined out of
    admission and a cooldown-gated trial re-admits it. Its active
    requests' device-side cache died with it, but their host-side
    journals didn't: with ``replay_attempts`` > 0 (default: the
    ``generation_replay_attempts`` flag) they re-queue head-of-line
    and re-prefill ``prompt ⊕ tokens`` into a healthy session —
    token-for-token identical output, zero client-visible errors;
    with replay off they resolve exceptionally. ``step_timeout_ms``
    bounds each session's step so one wedged device call can't freeze
    the dispatcher, and ``rebuild_limit`` lets a broken session be
    reconstructed in the background (see the module docstring).

    ``drain()`` stops admission and serves everything accepted;
    ``close()`` is the bounded fast exit. ``swap_weights(params)``
    installs new values between decode steps (see method docs).
    """

    def __init__(self, sessions, max_queue=256, deadline_ms=None,
                 breaker_failures=None, breaker_cooldown_ms=None,
                 replay_attempts=None, rebuild_limit=None,
                 step_timeout_ms=None, autostart=True):
        if isinstance(sessions, GenerationSession):
            sessions = [sessions]
        if not sessions:
            raise ValueError("need at least one GenerationSession")
        self.sessions = list(sessions)
        # every session must make the SAME next-token decisions: a
        # replay journal only resumes bit-identically where the
        # decode policy is identical (the weights-version rule of the
        # fleet tier, applied inside one scheduler)
        fps = {(s.policy.fingerprint() if s.policy is not None
                else GREEDY_FINGERPRINT) for s in self.sessions}
        if len(fps) > 1:
            raise ValueError(
                "sessions disagree on decode policy (%s) — a replay "
                "journal is only re-drivable across sessions that "
                "make identical next-token decisions" % sorted(fps))
        self._policy_fp = fps.pop()
        self._sampled = any(s.sampled for s in self.sessions)
        self._q = queue.Queue(maxsize=max_queue)
        # dispatcher-local order-preserving buffer: items parked when
        # no slot is free right now, and re-queue overflow from the
        # deadline sweep (consumed before the queue)
        self._pending = collections.deque()
        # True while some waiting item MAY carry a deadline — gates
        # the per-tick expiry sweep, which would otherwise rotate the
        # whole bounded queue on every decode step for nothing
        self._has_deadlines = False
        self._closed = False
        self._thread = None
        self._wait_ewma = 0.0
        self._active = {}   # (session_index, slot) -> _GenRequest
        self._sched_id = next(_SCHED_SEQ)
        if deadline_ms is None:
            deadline_ms = _config.get_flag("serving_deadline_ms")
        self.default_deadline_ms = deadline_ms
        if breaker_failures is None:
            breaker_failures = _config.get_flag(
                "serving_breaker_failures")
        if breaker_cooldown_ms is None:
            breaker_cooldown_ms = _config.get_flag(
                "serving_breaker_cooldown_ms")
        if breaker_failures:
            # namespaced like the engine tier's "e<N>:<replica>" (PR
            # 7): a process running serving engines AND generation
            # schedulers publishes both families of per-replica health
            # gauges on the one registry — "g<N>:<session>" keeps them
            # from overwriting each other
            self._breakers = [
                ReplicaBreaker(i, breaker_failures,
                               float(breaker_cooldown_ms) / 1e3,
                               label="g%d:%d" % (self._sched_id, i))
                for i in range(len(self.sessions))]
        else:
            self._breakers = None
        # -- stateful-failure recovery (flags read HERE only: the
        # dispatcher loop never consults config, and at the defaults
        # none of the machinery below is exercised) ---------------------
        if replay_attempts is None:
            replay_attempts = _config.get_flag(
                "generation_replay_attempts")
        self.replay_attempts = int(replay_attempts or 0)
        if rebuild_limit is None:
            rebuild_limit = _config.get_flag("generation_rebuild_limit")
        self.rebuild_limit = int(rebuild_limit or 0)
        if step_timeout_ms is None:
            step_timeout_ms = _config.get_flag(
                "generation_step_timeout_ms")
        self.step_timeout = (float(step_timeout_ms) / 1e3
                             if step_timeout_ms else None)
        if self.step_timeout is not None and \
                any(s.speculative for s in self.sessions):
            raise ValueError(
                "step_timeout_ms does not compose with speculative "
                "decoding: the speculative round mutates the block "
                "pool inside step_run, which must stay on the "
                "dispatcher thread — a leaked bounded worker could "
                "race retire()/close() on the allocator books")
        self._wedged = {}        # si -> done-Event of the leaked step
        self._rebuilding = set()  # session indices down for rebuild
        # True only once NOTHING will absorb rebuilds anymore (the
        # dispatcher exited, or a dispatcherless close()/drain()
        # finished serving) — _closed alone is not it: a draining
        # scheduler is closed to admission but still absorbing
        self._terminal = False
        self._rebuilt = queue.Queue()  # (si, session|None, err, secs)
        self._rebuilds = [0] * len(self.sessions)
        self._trial_failures = [0] * len(self.sessions)
        self._swap_lock = threading.Lock()
        self._pending_swap = None  # (params, Future)
        self._weights_version = 0
        # live introspection: /healthz aggregates every live
        # scheduler's session view (weakref — GC drops it lazily,
        # the dispatcher-exit epilogue unregisters eagerly)
        from ..observability import health as _health
        self._health_name = "generation%d" % self._sched_id
        _health.register_health(self._health_name,
                              _scheduler_health(weakref.ref(self)))
        if autostart:
            self.start()

    # -- lifecycle -------------------------------------------------------
    def start(self):
        if self._closed:
            raise RuntimeError("scheduler is closed")
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="generation-scheduler",
                                            daemon=True)
            self._thread.start()
        return self

    @property
    def weights_version(self):
        return self._weights_version

    def session_health(self):
        if self._breakers is None:
            return ["closed"] * len(self.sessions)
        return [b.state for b in self._breakers]

    def policy_fingerprint(self):
        """The decode-policy fingerprint every session here shares
        (``"greedy"`` with no policy) — what the fleet worker acks so
        the router can gate journal reuse (serving/fleet.py)."""
        return self._policy_fp

    # -- admission -------------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               deadline_ms=None, timeout=None, on_token=None,
               seed=None, tenant=None):
        """Enqueue one prompt; returns a Future of its generated ids.

        ``max_new_tokens`` is capped by the slot capacity left after
        the prompt (cache bucket / position table). ``deadline_ms``
        (default: the scheduler's ``deadline_ms``, itself defaulting
        to the ``serving_deadline_ms`` flag; 0/None = none) bounds the
        WHOLE generation. ``timeout``: seconds to wait on a full
        queue before :class:`ServingOverloadError`. ``on_token``:
        optional observer called with each newly generated token on
        the dispatcher thread (the fleet tier's streaming hook —
        default None costs one attribute check per token). ``seed``:
        the request's decode-RNG seed under a sampled policy — minted
        fresh when None, pass one explicitly to reproduce a sampled
        generation exactly (the fleet router does, so every failover
        hop resumes the same trajectory). ``tenant``: the submitting
        tenant's id (the fleet worker forwards the envelope's) —
        worker-side sheds of tenant-tagged requests charge
        ``paddle_serving_tenant_shed_total{tenant=...}`` beside the
        global counter, and the trace carries the id; admission math
        itself is tenant-blind (quotas are the router's job)."""
        if self._closed:
            raise RuntimeError("scheduler is closed")
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        # the prompt must fit SOME session's buckets (placement later
        # routes it only to sessions that can take it); the decode
        # budget cap comes from the most permissive fitting session
        fitting = [s for s in self.sessions
                   if s.prompt_bucket(prompt.size) is not None]
        if not fitting:
            raise ValueError(
                "prompt length %d exceeds every session's largest "
                "prompt bucket (max %d)"
                % (prompt.size,
                   max(s.spec.prompt_buckets[-1]
                       for s in self.sessions)))
        cap = max(s.max_pos for s in fitting) - prompt.size + 1
        if cap < 1:
            raise ValueError(
                "prompt length %d leaves no decode capacity in any "
                "session's cache bucket" % prompt.size)
        explicit = max_new_tokens is not None
        max_new = cap if not explicit else min(int(max_new_tokens), cap)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline = None
        if deadline_ms:  # 0/None = no deadline, the PR-5 contract
            budget = float(deadline_ms) / 1e3
            if budget < 0:
                _sres.DEADLINE_EXCEEDED.inc()
                raise ServingDeadlineError(
                    "deadline budget %.1f ms already spent"
                    % float(deadline_ms))
            projected = self._wait_ewma * (1.0 + self._q.qsize())
            if projected > budget:
                # same geometric decay as the batcher: sheds must not
                # latch the estimate high on an idle queue
                self._wait_ewma *= (1.0 - _WAIT_ALPHA)
                _sres.SHED.inc()
                if tenant is not None:
                    _sres.TENANT_SHED.labels(
                        tenant=str(tenant)).inc()
                raise ServingOverloadError(
                    "shed: projected admission wait %.1f ms exceeds "
                    "the %.1f ms deadline budget"
                    % (projected * 1e3, budget * 1e3))
            deadline = time.monotonic() + budget
        if seed is None:
            seed = mint_seed() if self._sampled else 0
        item = _GenRequest(prompt, max_new, explicit, eos_id, deadline,
                           on_token=on_token, seed=int(seed),
                           tenant=None if tenant is None
                           else str(tenant))
        # minted at the front door (one attribute read when off),
        # carried on the item/journal through every queue, session,
        # and replay hop
        mint_kw = {}
        if item.tenant is not None:
            mint_kw["tenant"] = item.tenant
        item.ctx = _rtrace.mint("generation.submit",
                                prompt_len=int(prompt.size),
                                max_new=int(max_new), **mint_kw)
        try:
            self._q.put(item, block=True, timeout=timeout)
        except queue.Full:
            _sres.SHED.inc()
            # never entered the system: a rejection storm must not
            # churn real in-flight traces out of the bounded store
            _rtrace.discard(item.ctx)
            if item.tenant is not None:
                _sres.TENANT_SHED.labels(tenant=item.tenant).inc()
            raise ServingOverloadError(
                "generation queue full (%d pending)"
                % self._q.qsize()) from None
        if deadline is not None:
            # AFTER the put: the sweep recomputes the flag from queue
            # content, so this order can never strand a deadline item
            # behind a cleared flag
            self._has_deadlines = True
        if self._closed and self._thread is None:
            # raced a close()/drain() past its leftover sweep (the
            # batcher's shutdown race, same resolution: fail OUR
            # future idempotently and refuse the submit)
            _rtrace.discard(item.ctx)
            _resolve(item.future,
                     exception=RuntimeError("scheduler closed"))
            raise RuntimeError("scheduler is closed")
        return item.future

    # -- dispatcher ------------------------------------------------------
    def _next_item(self, block):
        """Next request to place: the parked buffer first (preserves
        order), then the queue. None when nothing is waiting."""
        if self._pending:
            return self._pending.popleft()
        try:
            if block:
                return self._q.get(timeout=0.05)
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def _fits(self, sess, item):
        """Can ``sess`` serve this request IN FULL — prompt bucket and
        enough cache capacity for the promised token budget? Placement
        on a smaller-cache session would silently retire the sequence
        early with reason 'capacity', under-delivering the budget
        submit() accepted. An implicit ("as much as fits") budget is
        satisfied by ANY fitting session — requiring the largest
        session's cap would strand idle smaller replicas.

        A replay re-admission prefills the whole journal (prompt plus
        tokens already generated), so its length — and therefore its
        prompt bucket, possibly a larger one than the original
        admission used — and its REMAINING budget are what must fit.
        For a fresh item both reduce to the original check.

        Paged sessions with the prefix cache armed get one more
        chance: when the FULL journal outgrew every bucket, a cached
        prefix may shrink the actual prefill window back under one
        (``window_fits``, side-effect-free) — dense sessions return
        the exact old verdict through the same short-circuit."""
        n = item.prompt.size + len(item.tokens)
        need = max(1, item.max_new - len(item.tokens)) \
            if item.explicit_budget else 1
        if sess.max_pos - n + 1 < need or \
                not sess.storable(n + need - 1):
            return False
        return sess.prompt_bucket(n) is not None or \
            sess.window_fits(item.history())

    def _is_wedged(self, si):
        """True while session ``si``'s timed-out step worker is still
        stuck — it must not be stepped or admitted into (its executor
        and cache state are mid-flight). Once the leaked worker
        finishes, the marker clears; the breaker (opened by the hang)
        still gates re-admission through a cooldown trial."""
        ev = self._wedged.get(si)
        if ev is None:
            return False
        if ev.is_set():
            self._wedged.pop(si, None)
            return False
        return True

    def _eligible_session(self, item, claim=False):
        """Index of a session that can take this request NOW
        (free slot + fitting bucket/capacity + breaker closed, or a
        cooldown-elapsed trial when nothing fitting is closed), or
        None. Wedged and mid-rebuild sessions are never eligible. The
        half_open transition — a trial admission is the probe — fires
        only with ``claim=True``, i.e. when an actual request is about
        to be admitted; a capacity poll must not burn a breaker's
        cooldown with no trial to run."""
        candidates = [i for i, s in enumerate(self.sessions)
                      if i not in self._rebuilding
                      and not self._is_wedged(i)
                      and s.free_slots() and self._fits(s, item)
                      and s.admit_ok(item.prompt.size
                                     + len(item.tokens))]
        if item.failed_on:
            # a session this request already failed on is the LAST
            # resort, breaker state notwithstanding: its breaker may
            # still be closed (sub-threshold after the at-most-once
            # charge), and replaying straight back would burn the
            # whole budget on the one broken session
            candidates.sort(key=lambda i: i in item.failed_on)
        if not candidates:
            return None
        if self._breakers is None:
            return candidates[0]
        closed = [i for i in candidates
                  if self._breakers[i].state == "closed"]
        if closed:
            return closed[0]
        now = time.monotonic()
        for i in candidates:
            breaker = self._breakers[i]
            if breaker.state == "half_open" or \
                    breaker.ready_to_probe(now):
                if claim:
                    breaker.to_half_open()
                return i
        return None

    def _recovery_pending(self, item):
        """True while a FINITE recovery will make a fitting session
        placeable for ``item``: a rebuild hand-over is on its way, or
        replay is armed and a fitting session's breaker is riding a
        cooldown toward a trial. Shutdown serving (serve-out / drain)
        waits these out instead of failing the request — the wait is
        bounded by the cooldown/rebuild plus the item's replay
        budget. All-closed breakers with no free slots (external slot
        holders) are NOT recovery: nothing here ever frees them."""
        for i, s in enumerate(self.sessions):
            if not self._fits(s, item):
                continue
            if i in self._rebuilding:
                return True
            if self.replay_attempts and self._breakers is not None \
                    and not self._is_wedged(i) \
                    and self._breakers[i].state != "closed":
                return True
        return False

    def _dispatchable_later(self, item):
        """True when some session fitting this request is healthy
        (or trial-ready) but merely out of free slots — a retiring
        sequence will make room — or is being rebuilt and will rejoin.
        A still-wedged session is NOT a reason to wait: nothing drains
        it unless a rebuild is in flight.

        With replay armed, an open breaker whose cooldown is still
        running also counts: the cooldown is finite, the trial
        admission is how the session re-enters, and the wait is
        bounded — by the request's deadline (the expiry sweep keeps
        covering parked items) and by its replay budget (each failed
        trial it is admitted into burns one). Fast-failing here
        instead would break the zero-client-error contract for the
        exact window recovery needs. Replay off keeps the PR-8
        honesty: quarantine-with-cooldown-pending fails fast."""
        for i, s in enumerate(self.sessions):
            if not self._fits(s, item):
                continue
            if i in self._rebuilding:
                return True
            if self._is_wedged(i):
                continue
            breaker = self._breakers[i] if self._breakers else None
            if breaker is None or \
                    breaker.state in ("closed", "half_open") or \
                    breaker.ready_to_probe():
                return True
            if self.replay_attempts and breaker.state == "open":
                return True
        return False

    def _resolve_err(self, item, exc):
        """Exceptional resolution WITH its trace ending: every failed
        request's span tree ends in a ``resolveError`` edge (deadline
        endings have their own ``deadlineExpired``), so the trace an
        operator pulls for a failure never just stops mid-life."""
        if item.ctx is not None:
            _rtrace.event(item.ctx, "resolveError",
                          error=repr(exc)[:200],
                          error_type=type(exc).__name__)
        _resolve(item.future, exception=exc)

    def _expire(self, item, where):
        _sres.DEADLINE_EXCEEDED.inc()
        if item.ctx is not None:
            _rtrace.event(item.ctx, "deadlineExpired", where=where,
                          replays=item.replays)
        _resolve(item.future, exception=ServingDeadlineError(
            "deadline expired after %.1f ms %s"
            % ((time.perf_counter() - item.t_submit) * 1e3, where)))

    def _expire_queued(self):
        """Resolve expired deadlines for requests still waiting — even
        while every slot is busy. The batcher drops expired items at
        every dispatch tick; a slot-starved stretch must not suspend
        that contract and leave a doomed caller blocked until some
        unrelated sequence retires. Gated by ``_has_deadlines`` so a
        deadline-free workload never pays the queue rotation."""
        if not self._has_deadlines:
            return
        now = time.monotonic()
        remaining = False
        keep = collections.deque()
        while self._pending:
            item = self._pending.popleft()
            if item is not _STOP and item.deadline is not None \
                    and now >= item.deadline:
                self._expire(item, "in queue")
            else:
                if item is not _STOP and item.deadline is not None:
                    remaining = True
                keep.append(item)
        self._pending = keep
        for _ in range(self._q.qsize()):
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP and item.deadline is not None \
                    and now >= item.deadline:
                self._expire(item, "in queue")
            else:
                if item is not _STOP and item.deadline is not None:
                    remaining = True
                try:
                    self._q.put_nowait(item)
                except queue.Full:
                    # a racing submit took the freed capacity: the
                    # parked buffer keeps the item dispatchable
                    self._pending.append(item)
        # recomputed from content — a submit landing mid-sweep re-arms
        # the flag itself after its put
        self._has_deadlines = remaining

    def _place(self, item):
        """Admit ``item`` somewhere, park it for later, or resolve it.
        Returns False when the item was parked (no capacity right now
        — the caller should stop pulling from the queue)."""
        if item.deadline is not None and \
                time.monotonic() >= item.deadline:
            self._expire(item, "in queue")
            return True
        si = self._eligible_session(item, claim=True)
        if si is None:
            if self._dispatchable_later(item):
                self._pending.appendleft(item)
                return False
            # nothing can ever take this request: fail explicitly
            # rather than wedging it in a queue nothing drains. For a
            # replay, surface the SESSION failure that parked it (a
            # generic unavailable error would mask it — e.g. when the
            # journal outgrew every prompt bucket, the caller should
            # see why the generation actually died).
            self._resolve_err(item, item.last_exc
                              if item.last_exc is not None
                              else ServingUnavailableError(
                                  "no healthy generation session for "
                                  "this prompt"))
            return True
        self._admit_item(item, si)
        return True

    def _admit_item(self, item, si):
        wait = time.perf_counter() - item.t_queued
        self._wait_ewma += _WAIT_ALPHA * (wait - self._wait_ewma)
        _rtrace.QUEUE_WAIT_MS.observe(wait * 1e3)
        sess = self.sessions[si]
        replay = bool(item.tokens)
        if item.ctx is not None:
            _rtrace.event(item.ctx, "queueWait", dur_ms=wait * 1e3,
                          replay=replay)
        t_admit0 = time.perf_counter()
        try:
            # the activated context follows the admission into the
            # fault hook and the prefill's executor.run (deviceCall
            # spans land on this request's trace)
            with _rtrace.activate(item.ctx):
                _faults.fire_point("generation_admit_fail", index=si)
                cstate = None
                if sess.constrained:
                    # replay state folds the journal through the
                    # automaton — the host state is journal-derived,
                    # exactly like the KV cache
                    c = sess.policy.constraint
                    cstate = c.advance_many(c.start, item.tokens)
                slot, first = sess.admit(item.history(),
                                         seed=item.seed, cstate=cstate)
        except ValueError as exc:
            # a client-shaped prompt (bucket/length) is the request's
            # fault, not the session's — it must not charge the
            # breaker and quarantine a healthy session
            self._resolve_err(item, exc)
            return
        except Exception as exc:
            self._on_admit_failure(item, si, exc)
            return
        # breaker success is recorded by a surviving STEP, not here: a
        # persistently step-broken session would otherwise launder
        # itself closed through every trial admission it then fails
        if item.eos_id is None:
            item.eos_id = sess.spec.eos_id
        now_pc = time.perf_counter()
        _rtrace.PREFILL_MS.observe((now_pc - t_admit0) * 1e3)
        if item.ctx is not None:
            # hist = prefix-cache hit length: tokens served from
            # shared blocks instead of re-prefilled (0 on the dense
            # layout and on a prefix miss)
            hist = sess.prefill_log[-1][1] \
                if getattr(sess, "paged", False) and sess.prefill_log \
                else 0
            _rtrace.event(item.ctx,
                          "replayAdmit" if replay else "prefill",
                          dur_ms=(now_pc - t_admit0) * 1e3,
                          session=si, slot=slot,
                          journal_len=int(item.prompt.size)
                          + len(item.tokens), hist=int(hist))
        if replay:
            # the same logical request, resumed — requests_total must
            # not double-count it; the re-prefilled history is what
            # the failover actually cost
            _REPLAYED_TOKENS.inc(len(item.tokens))
            _RECOVERY_SECONDS.observe(now_pc - item.t_queued)
            _rtrace.REPLAY_RECOVERY_MS.observe(
                (now_pc - item.t_queued) * 1e3)
        else:
            _REQUESTS.inc()
            _TTFT_SECONDS.observe(now_pc - item.t_submit)
        _TOKENS.inc()  # the prefill produced one NEW token either way
        item.t_last = now_pc
        item.slot = slot
        item.session_index = si
        item.tokens.append(first)
        item.notify_token(first)
        self._active[(si, slot)] = item
        self._update_occupancy()
        # EOS/budget can end it at token 1; a surviving constrained
        # request may already be in a dead automaton state
        if not self._finish_if_done(item):
            self._check_dead_end(sess, item)

    def _on_admit_failure(self, item, si, exc):
        """A session failed this request's (re-)admission: charge its
        breaker (at most once per request across all its replays —
        the poison-prompt discipline; a half-open trial failure always
        records, the PR-5 rule), then replay the request elsewhere or
        surface the failure when the budget is spent."""
        breaker = self._breakers[si] if self._breakers else None
        if breaker is not None:
            was_trial = breaker.state == "half_open"
            if was_trial or not item.charged:
                breaker.record_failure()
                item.charged = True
            if was_trial:
                self._trial_failures[si] += 1
        item.failed_on.add(si)
        if item.ctx is not None:
            _rtrace.event(item.ctx, "admitFailure", session=si,
                          trial=was_trial if breaker is not None
                          else False, error=repr(exc)[:200])
        _log.structured("generation_admit_failed", session=si,
                        error=repr(exc), replay=bool(item.tokens))
        self._maybe_rebuild(si)
        # no slot was held here, so no retirement to count either way
        self._requeue_for_replay([item], exc)

    def _requeue_for_replay(self, items, exc):
        """Park failed requests head-of-line for replay re-admission;
        items whose replay budget is spent resolve with ``exc``
        instead. Returns the list actually re-queued (slot/retirement
        accounting stays with the caller, which knows whether the
        items were holding slots)."""
        requeued, spent = [], []
        for item in items:
            if self.replay_attempts and \
                    item.replays < self.replay_attempts:
                requeued.append(item)
            else:
                spent.append(item)
        # appendleft in reverse keeps the failed batch's own order at
        # the head of the parked buffer (consumed before the queue)
        for item in reversed(requeued):
            item.replays += 1
            item.t_queued = time.perf_counter()
            item.last_exc = exc
            _FAILOVERS.inc()
            if item.ctx is not None:
                # the failover hop, from the journal's side: the next
                # replayAdmit event names the NEW session — together
                # they are the old-session -> new-session edge
                _rtrace.event(item.ctx, "failoverRequeue",
                              from_session=item.session_index,
                              replays=item.replays,
                              journal_len=int(item.prompt.size)
                              + len(item.tokens),
                              error=repr(exc)[:200])
            self._pending.appendleft(item)
        if any(item.deadline is not None for item in requeued):
            # the expiry sweep must keep covering parked replays: a
            # deadline that runs out while parked resolves WITHOUT
            # ever re-prefilling
            self._has_deadlines = True
        for item in spent:
            self._resolve_err(item, exc)
        return requeued

    def _finish_if_done(self, item):
        """Retire/resolve when EOS, budget, capacity, or deadline ends
        the sequence. Returns True when the request left its slot."""
        sess = self.sessions[item.session_index]
        reason = None
        if item.tokens and item.tokens[-1] == item.eos_id:
            item.tokens.pop()
            reason = "eos"
        elif len(item.tokens) >= item.max_new:
            reason = "max_tokens"
        elif sess.capacity_left(item.slot) <= 0:
            reason = "capacity"
        elif item.deadline is not None and \
                time.monotonic() >= item.deadline:
            reason = "deadline"
        if reason is None:
            return False
        sess.retire(item.slot)
        del self._active[(item.session_index, item.slot)]
        _RETIRED.labels(reason=reason).inc()
        if reason == "deadline":
            _sres.DEADLINE_EXCEEDED.inc()
            if item.ctx is not None:
                _rtrace.event(item.ctx, "deadlineExpired",
                              where="mid-generation",
                              tokens=len(item.tokens))
            _resolve(item.future, exception=ServingDeadlineError(
                "deadline expired mid-generation after %d tokens"
                % len(item.tokens)))
        else:
            e2e = time.perf_counter() - item.t_submit
            _REQUEST_SECONDS.observe(e2e)
            _rtrace.E2E_MS.observe(e2e * 1e3)
            if item.ctx is not None:
                _rtrace.event(item.ctx, "resolve", reason=reason,
                              tokens=len(item.tokens),
                              dur_ms=e2e * 1e3)
            _resolve(item.future,
                     result=np.asarray(item.tokens, np.int64))
        self._update_occupancy()
        return True

    def _check_dead_end(self, sess, item):
        """Constraint dead end: the automaton state a just-landed
        token advanced into bans EVERY next token. Resolved as a
        typed CLIENT error — no breaker charge, no replay, and above
        all no hang (an all--inf mask row would otherwise argmax
        garbage forever). The ``decode_constraint_dead_end`` fault
        site forces this path for chaos tests. Returns True when the
        request left its slot."""
        if not sess.constrained:
            return False
        key = (item.session_index, item.slot)
        if key not in self._active:
            return False
        state = sess.cstate[item.slot]
        fired = _faults.should_fire("decode_constraint_dead_end",
                                    index=item.slot)
        if fired is None and not sess.policy.constraint.dead(state):
            return False
        sess.retire(item.slot)
        del self._active[key]
        _RETIRED.labels(reason="dead_end").inc()
        from .decoding import ConstraintDeadEnd
        self._resolve_err(
            item, ConstraintDeadEnd(state, len(item.tokens)))
        self._update_occupancy()
        return True

    def _step_session(self, si, sess, prepared=None):
        """One session's decode step plus its fault hooks — shared by
        the inline path and the bounded worker, so injected faults
        (including a wedge callback) land inside whatever bounds the
        step. ``prepared`` carries a host-side step_prepare() handle
        when the caller already ran phase 1 — _step_all does on both
        paths, keeping pool mutation on the dispatcher thread and
        outside any request's activated trace context."""
        _faults.fire_point("generation_session_wedge", index=si)
        _faults.fire_point("generation_step_fail", index=si)
        if prepared is not None:
            return sess.step_run(prepared)
        return sess.step()

    def _step_timed(self, si, sess, prepared):
        """Step bounded by ``self.step_timeout`` on a worker thread
        (resilience.run_bounded). A hang raises ServingTimeoutError
        and marks the session wedged — its stuck worker is leaked and
        CAPPED at one: the wedge marker keeps the session out of
        placement and stepping until the thread finishes, so retries
        can't stack blocked threads behind a dead device call.

        ``prepared`` is the session's step_prepare() handle, produced
        by _step_all on the dispatcher thread — which on the paged
        layout is where ALL block-pool mutation happens: a worker
        leaked past its timeout only ever executes the device call
        plus per-slot scalar advances, never allocator mutation, so
        it cannot race the dispatcher's retire()/close() on the pool
        accounting."""
        try:
            return _sres.run_bounded(
                lambda: self._step_session(si, sess, prepared),
                self.step_timeout,
                name="generation-step-%d" % si)
        except _sres.ServingTimeoutError as err:
            pending = getattr(err, "pending", None)
            if pending is not None:
                self._wedged[si] = pending
            _STEP_TIMEOUTS.inc()
            raise

    def _on_session_failure(self, si, sess, mine, exc, hang=False):
        """A session's step failed (or hung): free its slots, charge
        its breaker once for the event, and replay the affected
        requests into healthy sessions (default-off: they resolve
        exceptionally, the pre-replay contract). The cache state died
        with the session, but each request's prompt+tokens journal is
        a complete deterministic transcript — re-prefilling it
        elsewhere resumes the generation with identical output."""
        breaker = self._breakers[si] if self._breakers else None
        if breaker is not None:
            # one breaker charge per failure EVENT (the step is the
            # unit of failure, not the co-batched requests on it) —
            # and at most one per REQUEST across its replays: when
            # every affected request has already charged a breaker
            # elsewhere, this event is those suspects re-failing (the
            # poison shape), and charging again would let one bad
            # request quarantine session after session. Hangs are
            # always the session's fault, and a half-open trial
            # failure must always record (the PR-5 rules).
            was_trial = breaker.state == "half_open"
            uncharged = [it for _, it in mine if not it.charged]
            if hang or was_trial or uncharged:
                breaker.record_failure(hang=hang)
                for it in uncharged:
                    it.charged = True
            if was_trial:
                self._trial_failures[si] += 1
        _log.structured("generation_step_failed", session=si,
                        error=repr(exc), hang=hang, requests=len(mine))
        for slot, it in mine:
            sess.retire(slot)
            self._active.pop((si, slot), None)
            it.failed_on.add(si)
            if it.ctx is not None:
                _rtrace.event(it.ctx, "sessionFailure", session=si,
                              slot=slot, hang=hang,
                              error=repr(exc)[:200])
        items = [it for _, it in mine]
        requeued = set()
        if self.replay_attempts:
            requeued = set(map(id, self._requeue_for_replay(items, exc)))
        else:
            for it in items:
                self._resolve_err(it, exc)
        for it in items:
            _RETIRED.labels(
                reason="failover" if id(it) in requeued
                else "error").inc()
        self._update_occupancy()
        # a wedged session can't run cooldown trials at all — when
        # rebuild is armed it goes straight to reconstruction
        self._maybe_rebuild(si, force=hang)

    def _step_all(self):
        for si, sess in enumerate(self.sessions):
            if si in self._rebuilding:
                continue  # down for reconstruction; nothing is active
            mine = [(slot, it) for (s_i, slot), it
                    in list(self._active.items()) if s_i == si]
            if not mine:
                continue
            breaker = self._breakers[si] if self._breakers else None
            # one decode program serves every co-resident request:
            # the step's deviceCall span is carried by the FIRST
            # sampled request's context (the inline path; a
            # worker-bounded step loses it by design), each sampled
            # request then gets its own slot-annotated decodeStep
            # event below
            step_ctx = next((it.ctx for _, it in mine
                             if it.ctx is not None), None)
            t_step0 = time.perf_counter()
            try:
                # step_prepare runs OUTSIDE the activated context on
                # both paths: its paged pool mutations (grow, COW,
                # eviction pressure) are batch-level — slot B's COW
                # must not land in request A's span tree, so those
                # global events reach only the flight ring
                prepared = sess.step_prepare()
                if prepared is None:
                    toks = {}
                elif self.step_timeout is not None:
                    toks = self._step_timed(si, sess, prepared)
                else:
                    with _rtrace.activate(step_ctx):
                        toks = self._step_session(si, sess, prepared)
            except Exception as exc:
                hang = isinstance(exc, _sres.ServingTimeoutError)
                self._on_session_failure(si, sess, mine, exc,
                                         hang=hang)
                continue
            if breaker is not None:
                breaker.record_success()
                self._trial_failures[si] = 0
            _STEPS.inc()
            now_pc = time.perf_counter()
            step_ms = (now_pc - t_step0) * 1e3
            _rtrace.DECODE_STEP_MS.observe(step_ms)
            advanced = 0
            for slot, it in mine:
                if slot not in toks:
                    # paged pool exhausted for this sequence (no
                    # allocatable block even after eviction): it
                    # cannot grow HERE. Dense sessions never omit an
                    # active slot, so this branch costs them nothing.
                    sess.retire(slot)
                    del self._active[(si, slot)]
                    self._update_occupancy()
                    if self.replay_attempts and it.explicit_budget \
                            and len(it.tokens) < it.max_new and \
                            it.replays < self.replay_attempts:
                        # preemption, not truncation: the journal
                        # re-queues and resumes BIT-identically once
                        # blocks free (admit_ok parks it meanwhile) —
                        # possibly on a less contended session, which
                        # placement prefers via failed_on. Only an
                        # exhausted replay budget falls through to
                        # the capacity finish below.
                        from .paged_cache import PoolExhausted
                        it.failed_on.add(si)
                        _RETIRED.labels(reason="preempted").inc()
                        if it.ctx is not None:
                            _rtrace.event(it.ctx, "preempted",
                                          session=si, slot=slot,
                                          tokens=len(it.tokens))
                        self._requeue_for_replay(
                            [it], PoolExhausted(
                                "session %d pool exhausted after %d "
                                "tokens" % (si, len(it.tokens))))
                        continue
                    # implicit budgets asked for "as much as fits":
                    # finishing at the current length IS the
                    # contract — the 'capacity' retirement, reached
                    # through pool bytes instead of the position
                    # table
                    _RETIRED.labels(reason="capacity").inc()
                    _REQUEST_SECONDS.observe(now_pc - it.t_submit)
                    _rtrace.E2E_MS.observe((now_pc - it.t_submit) * 1e3)
                    if it.ctx is not None:
                        _rtrace.event(it.ctx, "resolve",
                                      reason="capacity",
                                      tokens=len(it.tokens))
                    _resolve(it.future,
                             result=np.asarray(it.tokens, np.int64))
                    continue
                got = toks[slot]
                # a speculative round emits a LIST per slot — the
                # accepted draft prefix plus the correction/bonus
                # token; plain rounds stay a bare int
                for tok in (got if isinstance(got, list) else [got]):
                    advanced += 1
                    it.tokens.append(tok)
                    it.notify_token(tok)
                    _INTER_TOKEN_SECONDS.observe(now_pc - it.t_last)
                    it.t_last = now_pc
                    if it.ctx is not None:
                        _rtrace.event(it.ctx, "decodeStep",
                                      dur_ms=step_ms, session=si,
                                      slot=slot, active=len(mine),
                                      token_index=len(it.tokens))
                    if self._finish_if_done(it) or \
                            self._check_dead_end(sess, it):
                        # EOS/budget/dead-end mid-window: the round
                        # could not know — the rest of the list is
                        # discarded with the slot already retired
                        break
            _TOKENS.inc(advanced)

    # -- session rebuild -------------------------------------------------
    def _maybe_rebuild(self, si, force=False):
        """Kick off a background teardown/reconstruct of session
        ``si`` when it has proven broken: its post-quarantine trial
        re-admissions keep failing (>= _REBUILD_AFTER_TRIALS), or
        ``force`` (a wedge — trials are impossible). Bounded by
        ``rebuild_limit`` per session; needs ``spec.rebuild``."""
        if not self.rebuild_limit or si in self._rebuilding:
            return
        if self._rebuilds[si] >= self.rebuild_limit:
            return
        if not force and self._trial_failures[si] < _REBUILD_AFTER_TRIALS:
            return
        sess = self.sessions[si]
        if sess.spec.rebuild is None:
            return
        if any(s_i == si for (s_i, _) in self._active):
            return  # live requests still decoding there; next event
        self._rebuilding.add(si)
        self._rebuilds[si] += 1
        # a rebuild is incident-grade (quarantine became repair):
        # annotate the active request's trace and snapshot the flight
        # ring while the lead-up events are still in it
        _rtrace.global_event("sessionRebuildStart", session=si,
                            forced=bool(force),
                            rebuilds=self._rebuilds[si])
        _flight.RECORDER.trigger_async("session_rebuild", session=si,
                                       forced=bool(force))
        threading.Thread(
            target=self._rebuild_worker, args=(si, sess),
            name="generation-rebuild-%d" % si, daemon=True).start()

    # Bound on one rebuild's construct + warmup (covers fresh XLA
    # compiles, which reach tens of seconds on a real chip): a rebuild
    # was triggered because the session was broken — possibly a DEAD
    # device — and an unbounded warmup against it would pin
    # _rebuilding forever, parking every request that fits only this
    # session and spinning shutdown serving for good.
    REBUILD_TIMEOUT = 120.0

    def _rebuild_worker(self, si, old_sess):
        """Background thread: construct the replacement session —
        fresh spec (new cache namespace), params re-read from the same
        scope, cache zeros re-materialized — and warm every prompt
        bucket's prefill plus the decode program so the executor
        compiles land before it takes traffic. The whole build is
        bounded by REBUILD_TIMEOUT (a dead device must fail the
        rebuild, not hang it). Hand-over happens on the dispatcher
        thread (_absorb_rebuilds); only the build runs here."""
        t0 = time.perf_counter()
        # abandon handshake: the builder COMMITS its session and the
        # timed-out waiter ABANDONS under one lock, and whichever
        # loses the race releases the session — a build finishing in
        # the instant the bounded wait gives up must not leak its
        # cache claims/arrays into nowhere
        state = {"abandoned": False, "new": None}
        state_lock = threading.Lock()

        def build():
            new = None
            try:
                spec = old_sess.spec.rebuild()
                new = GenerationSession(spec, scope=old_sess.scope,
                                        place=old_sess.place)
                # warm EVERY prompt bucket plus the decode program:
                # the hand-over must not leave a bucket whose first
                # live (or replay-promoted) request pays an XLA
                # compile stall on the dispatcher thread. The prefix
                # index is detached for the warmups: otherwise a
                # later bucket's warm prompt matches an earlier one's
                # cached prefix, the SUFFIX picks a smaller program,
                # and the large bucket never actually compiles (and
                # warm-junk tokens would stay pinned in the index).
                prefix, new.prefix = new.prefix, None
                try:
                    for bucket in spec.prompt_buckets:
                        n = max(1, min(int(bucket), new.max_pos))
                        slot, _ = new.admit([spec.bos_id] * n)
                        new.retire(slot)
                    slot, _ = new.admit([spec.bos_id])
                    new.step()
                    new.retire(slot)
                    if new.paged and spec.copy_program is not None:
                        # the COW program too (block 0 onto itself is
                        # a harmless identity copy)
                        new._copy_block(0, 0)
                finally:
                    new.prefix = prefix
            except BaseException:
                if new is not None:
                    try:
                        new.close()
                    except Exception:
                        pass
                raise
            with state_lock:
                if not state["abandoned"]:
                    state["new"] = new  # committed
                    return new
            # the bounded wait gave up on us: release rather than
            # hand a session to nobody
            try:
                new.close()
            except Exception:
                pass
            return None

        try:
            new = _sres.run_bounded(
                build, self.REBUILD_TIMEOUT,
                name="generation-rebuild-build-%d" % si)
        except Exception as exc:
            with state_lock:
                state["abandoned"] = True
                committed = state["new"]
                state["new"] = None
            if committed is not None:
                # the build committed in the instant we gave up
                try:
                    committed.close()
                except Exception:
                    pass
            self._rebuilt.put((si, None, exc,
                               time.perf_counter() - t0))
            return
        if new is None:  # abandoned race: already released
            self._rebuilt.put((si, None,
                               RuntimeError("rebuild abandoned"),
                               time.perf_counter() - t0))
            return
        if self._terminal:
            # the scheduler is fully shut down mid-build (a merely
            # DRAINING scheduler still absorbs — parked requests may
            # be waiting on exactly this hand-over): nobody will
            # absorb the replacement — release its cache
            # claims/arrays instead of leaking them
            try:
                new.close()
            except Exception:
                pass
            self._rebuilding.discard(si)
            return
        self._rebuilt.put((si, new, None, time.perf_counter() - t0))
        if self._terminal:
            # shutdown raced the put past its final sweep: drain our
            # own hand-over (idempotent with that sweep)
            self._drain_rebuilt()

    def _absorb_rebuilds(self):
        """Dispatcher-thread hand-over: swap finished rebuilds into
        the session list (the dispatcher is the only session caller,
        so the swap is race-free) and re-admit them."""
        if not self._rebuilding:
            # nothing can be in the queue (entries join _rebuilding
            # before their worker starts): the default-off dispatcher
            # tick pays one truthiness check, not a queue lock +
            # caught queue.Empty
            return
        while True:
            try:
                si, new, err, secs = self._rebuilt.get_nowait()
            except queue.Empty:
                return
            self._rebuilding.discard(si)
            if new is None:
                _log.structured("generation_rebuild_failed",
                                session=si, error=repr(err),
                                rebuilds=self._rebuilds[si])
                continue  # budget permitting, a later event retries
            old = self.sessions[si]
            try:
                # release the old claim and drop the old cache arrays;
                # a still-wedged step finishing later republishes only
                # the ORPHANED old names (the new namespace is why)
                old.close()
            except Exception:
                pass
            self.sessions[si] = new
            self._wedged.pop(si, None)
            self._trial_failures[si] = 0
            if self._breakers is not None:
                # fresh warmed session: straight back into rotation
                self._breakers[si].record_success()
            _REBUILDS.inc()
            _rtrace.global_event("sessionRebuilt", session=si,
                                 seconds=round(secs, 3))
            _log.structured("generation_session_rebuilt", session=si,
                            seconds=round(secs, 3),
                            rebuilds=self._rebuilds[si])

    def _update_occupancy(self):
        total = sum(s.spec.slots for s in self.sessions)
        _OCCUPANCY.labels(scheduler="gen%d" % self._sched_id).set(
            len(self._active) / float(total))

    def _apply_pending_swap(self):
        with self._swap_lock:
            pending, self._pending_swap = self._pending_swap, None
        if pending is None:
            return
        params, future = pending
        try:
            scopes = []
            for sess in self.sessions:
                if sess.scope not in scopes:
                    scopes.append(sess.scope)
            cache_names = {name for s in self.sessions
                           for name, _, _ in s.spec.cache_vars}
            # phase 1: validate EVERY scope before mutating ANY — a
            # rejection on the second scope must not leave the first
            # already serving the rejected weights (torn swap)
            for scope in scopes:
                for name, val in params.items():
                    if name in cache_names:
                        raise ValueError(
                            "refusing to overwrite cache variable %r"
                            % name)
                    cur = scope.find_var(name)
                    if cur is None:
                        raise ValueError(
                            "swap names unknown variable %r" % name)
                    val = np.asarray(val)
                    # metadata-only checks: materializing live device
                    # params on host here would stall the decode loop
                    # for a full model D2H copy per swap
                    cur_shape = tuple(np.shape(cur))
                    cur_dtype = np.dtype(cur.dtype) \
                        if hasattr(cur, "dtype") \
                        else np.asarray(cur).dtype
                    if tuple(val.shape) != cur_shape or \
                            val.dtype != cur_dtype:
                        raise ValueError(
                            "signature mismatch on %r: push %s/%s vs "
                            "live %s/%s"
                            % (name, val.shape, val.dtype,
                               cur_shape, cur_dtype))
            # phase 2: install everywhere (pure pointer installs —
            # nothing here can raise and tear the fleet)
            for scope in scopes:
                for name, val in params.items():
                    scope.set_var(name, np.asarray(val))
            self._weights_version += 1
            _log.structured("generation_weights_swapped",
                            version=self._weights_version,
                            params=len(params))
            _resolve(future, result=self._weights_version)
        except Exception as exc:
            _resolve(future, exception=exc)

    def swap_weights(self, params, timeout=30.0):
        """Install new parameter values (``{name: array}``) on every
        session's scope BETWEEN decode steps — the hot-swap story for
        stateful serving. The flip lands on a step boundary (the
        dispatcher applies it before its next admit/step), so no
        forward pass mixes versions; sequences already mid-generation
        continue on the new weights, which is the documented semantic
        for session state (their KV cache keeps the old weights'
        values — retire-and-retry callers who need strict isolation).
        Cache variables are refused; name/shape/dtype mismatches
        reject the push. Returns the new weights version."""
        if self._closed:
            raise RuntimeError("scheduler is closed")
        future = Future()
        with self._swap_lock:
            if self._pending_swap is not None:
                raise RuntimeError("a weight swap is already pending")
            self._pending_swap = (dict(params), future)
        if self._thread is None:
            self._apply_pending_swap()
        try:
            return future.result(timeout=timeout)
        except FutureTimeoutError:
            with self._swap_lock:
                if self._pending_swap is not None and \
                        self._pending_swap[1] is future:
                    # still queued: cancel it so the "failed" push can
                    # never land silently later, and a retry isn't
                    # blocked by a phantom pending swap
                    self._pending_swap = None
                    raise RuntimeError(
                        "weight swap not applied within %.0fs — "
                        "cancelled" % timeout) from None
            # the dispatcher picked it up mid-wait: the install is a
            # bounded pointer flip, give it a moment to land
            return future.result(timeout=5.0)

    def _serve_out(self):
        """Post-stop epilogue, on the dispatcher thread: finish every
        active slot AND place-and-serve everything still waiting —
        including submits that raced the stop marker into the queue
        (a timed-out close()/drain() join leaves this thread sole
        owner of the queues, so an unserved straggler here would be a
        Future nothing ever resolves). Waiting items co-batch into
        free slots like live traffic."""
        while True:
            self._apply_pending_swap()
            self._absorb_rebuilds()
            if self._active:
                self._step_all()
                continue
            item = self._next_item(block=False)
            if item is None:
                return
            if item is _STOP:
                continue
            if not self._place(item) and not self._active:
                if self._recovery_pending(item):
                    # a rebuild hand-over or a breaker cooldown trial
                    # will make room in finite time: the parked
                    # request is served then, not failed now
                    time.sleep(0.02)
                    continue
                # unplaceable with nothing in flight (external slot
                # holders): resolve rather than spinning forever
                parked = self._pending.popleft()
                self._resolve_err(parked, parked.last_exc
                                  if parked.last_exc is not None
                                  else ServingUnavailableError(
                                      "scheduler stopped before the "
                                      "request could be placed"))

    def _dispatcher_exit(self):
        """Dispatcher epilogue: nothing absorbs rebuilds past this
        point, so mark terminal and release any stragglers (the
        rebuild worker double-checks the flag around its put, closing
        the hand-over race from its side). Health-gauge children
        retire here too: this epilogue is the one point EVERY
        shutdown shape reaches — including a drain() whose bounded
        join expired and whose caller never calls close()."""
        self._terminal = True
        self._drain_rebuilt()
        self._retire_breaker_gauges()
        from ..observability import health as _health
        _health.unregister_health(getattr(self, "_health_name", ""))

    def _loop(self):
        try:
            self._loop_inner()
        finally:
            self._dispatcher_exit()

    def _loop_inner(self):
        while True:
            self._apply_pending_swap()
            self._absorb_rebuilds()
            if self._active:
                self._expire_queued()
                got_stop = self._fill_slots()
                self._step_all()
                if got_stop:
                    self._serve_out()
                    return
            else:
                # parked replay items may be waiting out a rebuild
                # with nothing active — their deadlines must keep
                # firing meanwhile (gated by _has_deadlines, so a
                # deadline-free workload pays an attribute check)
                self._expire_queued()
                item = self._next_item(block=True)
                if item is None:
                    if self._closed:
                        return
                    continue
                if item is _STOP:
                    self._serve_out()  # stragglers behind the marker
                    return
                if not self._place(item):
                    # parked with nothing active: only possible while
                    # every fitting session's slots are held outside
                    # this scheduler or a rebuild is in flight — back
                    # off instead of spinning
                    time.sleep(0.02)

    def _fill_slots(self):
        """Admit waiting requests into free slots without blocking.
        Returns True when the stop marker was consumed."""
        while True:
            item = self._next_item(block=False)
            if item is None:
                return False
            if item is _STOP:
                return True
            if not self._place(item):
                return False  # head parked: no capacity this tick

    # -- shutdown --------------------------------------------------------
    def _stop_dispatcher(self, timeout):
        self._closed = True
        if self._thread is not None:
            try:
                self._q.put_nowait(_STOP)
            except queue.Full:
                pass
            self._thread.join(timeout)
            if self._thread.is_alive():
                # the dispatcher is still finishing in-flight
                # generations past the bounded wait: it OWNS the
                # queues (sweeping them from under a live thread
                # races its every tick) and will serve what it holds
                # and exit on closed. Leave everything to it.
                return []
            self._thread = None
        leftovers = [item for item in self._pending if item is not _STOP]
        self._pending.clear()
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                leftovers.append(item)
        return leftovers

    def drain(self, timeout=None):
        """Graceful drain: stop admission, generate every accepted
        request to completion — in-flight slots AND waiting submits
        (parked or racing the stop marker; served synchronously here,
        slot by slot) — then stop. Every accepted Future resolves."""
        leftovers = self._stop_dispatcher(timeout)
        if self._thread is not None:
            # bounded join expired with the dispatcher still serving:
            # it finishes and resolves everything it holds on its own
            # thread (_serve_out) — two threads must not step the
            # same sessions
            return
        # dispatcher never started or was wedged: serve the remainder
        # here, co-batching waiting requests into free slots (placing
        # one-at-a-time would run each generation solo and forfeit
        # the batching this layer exists for)
        self._pending.extend(leftovers)
        while self._pending or self._active:
            self._absorb_rebuilds()
            progressed = False
            while self._pending:
                if not self._place(self._pending.popleft()):
                    break  # head parked again: a step must free slots
                progressed = True
            if self._active:
                self._step_all()
            elif not progressed and self._pending:
                if self._recovery_pending(self._pending[0]):
                    # a rebuild hand-over or cooldown trial serves
                    # the parked items in finite time
                    time.sleep(0.02)
                    continue
                # unplaceable with nothing in flight (external slot
                # holders): resolve rather than spinning forever
                parked = self._pending.popleft()
                self._resolve_err(parked, parked.last_exc
                                  if parked.last_exc is not None
                                  else ServingUnavailableError(
                                      "drain: no session could take "
                                      "the request"))
        self._dispatcher_exit()  # retires the health gauges too

    def _drain_rebuilt(self):
        """Terminal sweep (close()/drain(), or the rebuild worker
        itself when it races a close): completed rebuilds that no
        dispatcher will ever absorb are released — their cache
        claims and device arrays must not outlive the scheduler."""
        while True:
            try:
                si, new, _err, _secs = self._rebuilt.get_nowait()
            except queue.Empty:
                return
            self._rebuilding.discard(si)
            if new is not None:
                try:
                    new.close()
                except Exception:
                    pass

    def _retire_breaker_gauges(self):
        """Drop this scheduler's per-session health-gauge children so
        redeploy cycles don't accumulate stale labels on the shared
        registry (the engine tier's close() discipline); ``retired``
        keeps a straggling transition from resurrecting a child."""
        if self._breakers is None:
            return
        for breaker in self._breakers:
            breaker.retired = True
        # the registry-level sweep retires every family labelled on
        # this scheduler's "g<N>:*" namespace in one pass (the PR-9
        # per-child removal, generalized)
        _metrics.REGISTRY.remove_labeled(
            "replica", prefix="g%d:" % self._sched_id)

    def close(self, timeout=5.0):
        """Fast exit: a live dispatcher serves out everything it owns
        (active slots AND accepted submits) before exiting — past the
        bounded join it keeps doing so on its own thread — so no
        accepted Future is ever left hanging; with no dispatcher
        running, queued requests are failed instead."""
        for item in self._stop_dispatcher(timeout):
            self._resolve_err(item, RuntimeError("scheduler closed"))
        if self._thread is None:
            # dispatcher gone (or never started): nothing absorbs
            # rebuilds anymore; a live dispatcher past the bounded
            # join runs the same epilogue itself when it exits
            self._dispatcher_exit()
        self._retire_breaker_gauges()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
