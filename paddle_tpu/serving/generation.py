"""Autoregressive generation serving: on-device KV-cache sessions and
a continuous-batching scheduler.

The PR-2/5/7 serving stack is stateless — every request is one padded
batch through one compiled bucket. An LLM request is a *session*: a
prompt is prefilled once, then the model is stepped token by token
against per-sequence state (the KV cache) that must live on device
between steps. This module adds that stateful tier on top of the same
machinery:

* :class:`GenerationSession` — owns one decode batch: ``slots``
  sequences, each with a per-layer [slots, cache_len, d_model] K/V
  cache resident in a Scope as persistable variables. ``admit()`` runs
  a prompt-bucket prefill program that fills ONE slot's cache rows and
  returns the first greedy token; ``step()`` runs the single decode
  program — one token per slot, per-slot positions — so sequences at
  different depths decode together. Both programs are compiled exactly
  once per shape (the executor's compile cache sees a closed set:
  one decode entry per (slot-bucket, cache-bucket), one prefill entry
  per prompt bucket — asserted via ``Executor.compile_stats()``), and
  the caches ride the executor's donated state update: every step is
  an in-place ``dynamic_update_slice`` in HBM, never a cache copy.

* :class:`GenerationScheduler` — continuous batching:
  ``submit(prompt) -> Future`` with the MicroBatcher's admission
  discipline (bounded-queue backpressure -> ServingOverloadError,
  queue-wait EWMA shedding of hopeless deadlines, per-request
  deadlines -> ServingDeadlineError), a dispatcher thread that admits
  new sequences into free cache slots and retires finished ones
  mid-flight — slot-level, never a whole-batch flush: other sequences
  keep decoding through every admit/retire — plus the engine tier's
  recovery vocabulary: a :class:`ReplicaBreaker` per session
  quarantines a failing session out of admission (trial re-admission
  after cooldown), ``drain()`` serves everything accepted before
  stopping (the redeploy story), and ``swap_weights()`` installs new
  parameter values between decode steps (the deploy-tier hot swap,
  composed with stateful sessions: the flip lands on a step boundary,
  so no single forward pass ever mixes weight versions).

Nothing here is constructed by default flags: with no session built,
the serving fast path, the batcher, and the executor step are
untouched (the generation_* flags are read only inside constructors).

Metrics (always-on, like the serving front door):
``paddle_generation_requests_total``, ``_tokens_total``,
``_prefills_total``, ``_decode_steps_total``,
``_retired_total{reason}``, ``_slot_occupancy``,
``_ttft_seconds`` (time to first token), ``_inter_token_seconds``,
``_request_seconds``. Shed/deadline events share the serving counters
(``paddle_serving_shed_total`` / ``_deadline_exceeded_total``).
Fault site: ``generation_step_fail`` (indexed by session).
"""

import collections
import itertools
import queue
import threading
import time
import weakref
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError

import numpy as np

from .. import config as _config
from ..core.executor import Executor
from ..core.scope import global_scope
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing
from ..resilience import faults as _faults
from ..utils import log as _log
from . import resilience as _sres
from .batcher import ServingOverloadError, _resolve, _WAIT_ALPHA
from .resilience import (ReplicaBreaker, ServingDeadlineError,
                         ServingUnavailableError)

__all__ = ["GenerationSpec", "GenerationSession", "GenerationScheduler"]

_REQUESTS = _metrics.REGISTRY.counter(
    "paddle_generation_requests_total",
    "Generation requests admitted into a cache slot")
_TOKENS = _metrics.REGISTRY.counter(
    "paddle_generation_tokens_total",
    "Tokens decoded across all sequences (prefill's first token "
    "included)")
_PREFILLS = _metrics.REGISTRY.counter(
    "paddle_generation_prefills_total",
    "Prompt prefills executed, per prompt bucket",
    labelnames=("bucket",))
_STEPS = _metrics.REGISTRY.counter(
    "paddle_generation_decode_steps_total",
    "Decode steps executed (one per session step, all slots at once)")
_RETIRED = _metrics.REGISTRY.counter(
    "paddle_generation_retired_total",
    "Sequences retired from their slot", labelnames=("reason",))
_OCCUPANCY = _metrics.REGISTRY.gauge(
    "paddle_generation_slot_occupancy",
    "Active sequences / total cache slots across one scheduler's "
    "sessions (labelled per scheduler — two engines side by side "
    "must not overwrite each other)", labelnames=("scheduler",))
_TTFT_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_generation_ttft_seconds",
    "Submit -> first token latency (queue wait + prefill)")
_INTER_TOKEN_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_generation_inter_token_seconds",
    "Per-sequence latency between consecutive tokens")
_REQUEST_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_generation_request_seconds",
    "Submit -> Future resolution for completed generations")

_STOP = object()

# distinguishes per-session breaker gauge labels across schedulers
_SCHED_SEQ = itertools.count()

# scope -> set of cache-variable names already driven by a live
# session. Two sessions sharing cache names on one scope would
# silently corrupt each other's KV state (slot s of one overwrites
# rows the other's slot s attends), so construction refuses the
# collision — transformer_lm_session generates a fresh cache_ns per
# call, making a second spec the correct way to add a replica.
_CACHE_CLAIMS = weakref.WeakKeyDictionary()


class GenerationSpec:
    """The contract between a model's session builder (e.g.
    ``models.transformer.transformer_lm_session``) and the generic
    session/scheduler: programs plus the feed/fetch naming.

    * ``prefill_programs``: {prompt_bucket P: Program} — tokens
      [1, P] -> first greedy token [1], writing cache slot rows [0, P).
      ``prefill_feeds`` names (tokens, prompt_len, last_pos, slot).
    * ``decode_program``: one step for ALL slots — tokens [slots, 1] +
      positions [slots] -> next token per slot. ``decode_feeds`` names
      (tokens, positions).
    * ``cache_vars``: ((name, shape, dtype), ...) persistable cache
      variables a session materializes as device zeros in its scope.
    """

    __slots__ = ("slots", "cache_len", "max_len", "prompt_buckets",
                 "bos_id", "eos_id", "cache_vars", "prefill_programs",
                 "prefill_feeds", "prefill_fetch", "decode_program",
                 "decode_feeds", "decode_fetch")

    def __init__(self, **kwargs):
        for name in self.__slots__:
            setattr(self, name, kwargs.pop(name))
        if kwargs:
            raise TypeError("unknown GenerationSpec fields: %s"
                            % sorted(kwargs))


class GenerationSession:
    """One decode batch: ``spec.slots`` cache slots over one scope.

    Parameters are read from ``scope`` by name (run/load them first —
    a scope trained by the standard program, or a checkpoint/artifact
    restore); cache variables are created here as device zeros. All
    methods are single-threaded by contract: the scheduler's
    dispatcher thread is the only caller in the serving deployment.

    The executor compile cache stays CLOSED over a session's lifetime:
    every ``step()`` has the same (program, feed-signature) key, every
    ``admit()`` one key per prompt bucket — ``compile_stats()`` is the
    proof, asserted in tests and printed by tools/generate_probe.py.
    """

    def __init__(self, spec, scope=None, place=None):
        import jax.numpy as jnp
        self.spec = spec
        self.scope = scope if scope is not None else global_scope()
        self.exe = Executor(place=place)
        names = {name for name, _, _ in spec.cache_vars}
        claimed = _CACHE_CLAIMS.setdefault(self.scope, set())
        overlap = sorted(claimed & names)
        if overlap:
            raise ValueError(
                "cache variables %s on this scope are already driven "
                "by another GenerationSession — build a fresh spec "
                "(transformer_lm_session generates a unique cache_ns "
                "per call), or close() the old session" % overlap)
        claimed |= names
        self._claimed = names
        for name, shape, dtype in spec.cache_vars:
            if not self.scope.has_var(name):
                self.scope.set_var(name, jnp.zeros(shape, dtype))
        n = spec.slots
        self.lengths = np.zeros(n, np.int64)     # cached rows per slot
        self.last_token = np.zeros(n, np.int64)  # next token to decode
        self.active = np.zeros(n, bool)
        # the deepest position any sequence may WRITE: bounded by the
        # cache bucket and by the learned position table
        self.max_pos = min(spec.cache_len, spec.max_len)

    # -- slot bookkeeping ------------------------------------------------
    def free_slots(self):
        return [int(i) for i in np.flatnonzero(~self.active)]

    def active_slots(self):
        return [int(i) for i in np.flatnonzero(self.active)]

    def occupancy(self):
        return float(self.active.sum()) / self.spec.slots

    def capacity_left(self, slot):
        """Decode steps slot can still take before its cache bucket or
        position table runs out."""
        return int(self.max_pos - self.lengths[slot])

    def prompt_bucket(self, n):
        for p in self.spec.prompt_buckets:
            if n <= p:
                return p
        return None

    def compile_stats(self):
        return self.exe.compile_stats()

    def close(self):
        """Release this session's cache-variable claim (and drop the
        cache arrays from the scope), so a later session may reuse the
        names. Idempotent; the session must not be stepped after."""
        claimed = _CACHE_CLAIMS.get(self.scope)
        if claimed is not None:
            claimed -= self._claimed
        for name in self._claimed:
            self.scope.erase(name)
        self._claimed = set()
        self.active[:] = False

    # -- execution -------------------------------------------------------
    def admit(self, prompt):
        """Prefill ``prompt`` (1-D int ids) into a free slot: the
        prompt's K/V rows land in the cache, the slot becomes active,
        and the first greedy token is returned as ``(slot, token)``.
        Raises RuntimeError when no slot is free and ValueError when
        the prompt fits no bucket."""
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        n = prompt.size
        if n < 1:
            raise ValueError("empty prompt")
        bucket = self.prompt_bucket(n)
        if bucket is None:
            raise ValueError(
                "prompt length %d exceeds the largest prompt bucket %d"
                % (n, self.spec.prompt_buckets[-1]))
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free cache slot (%d active)"
                               % self.spec.slots)
        slot = free[0]
        padded = np.full((1, bucket), self.spec.eos_id, np.int64)
        padded[0, :n] = prompt
        f_tok, f_len, f_pos, f_slot = self.spec.prefill_feeds
        with _tracing.span("generationPrefill", bucket=bucket):
            outs = self.exe.run(
                self.spec.prefill_programs[bucket],
                feed={f_tok: padded,
                      f_len: np.asarray([n], np.int32),
                      f_pos: np.asarray([n - 1], np.int32),
                      f_slot: np.asarray([slot], np.int32)},
                fetch_list=[self.spec.prefill_fetch], scope=self.scope)
        first = int(np.asarray(outs[0]).reshape(-1)[0])
        self.lengths[slot] = n
        self.last_token[slot] = first
        self.active[slot] = True
        _PREFILLS.labels(bucket=bucket).inc()
        return slot, first

    def step(self):
        """One decode step for EVERY active slot: each slot's pending
        token is embedded at its own position, its K/V row appended in
        place, and its single query attended against the live cache
        prefix. Returns {slot: next_token} for active slots (free
        slots compute masked garbage that the next prefill
        overwrites). Raises RuntimeError when an active slot is out of
        cache capacity — retire it first."""
        act = np.flatnonzero(self.active)
        if act.size == 0:
            return {}
        if (self.lengths[act] >= self.max_pos).any():
            over = [int(s) for s in act
                    if self.lengths[s] >= self.max_pos]
            raise RuntimeError(
                "slots %s are at cache capacity %d — retire before "
                "stepping" % (over, self.max_pos))
        f_tok, f_pos = self.spec.decode_feeds
        with _tracing.span("generationStep",
                           active=int(act.size)):
            outs = self.exe.run(
                self.spec.decode_program,
                feed={f_tok: self.last_token.reshape(-1, 1),
                      f_pos: self.lengths.astype(np.int32)},
                fetch_list=[self.spec.decode_fetch], scope=self.scope)
        nxt = np.asarray(outs[0]).reshape(-1)
        result = {}
        for s in act:
            s = int(s)
            self.lengths[s] += 1
            self.last_token[s] = int(nxt[s])
            result[s] = int(nxt[s])
        return result

    def retire(self, slot):
        """Free a slot mid-flight. The cache rows are left as-is — the
        next prefill into this slot overwrites them, and the per-slot
        length mask keeps them unattendable meanwhile."""
        self.active[slot] = False
        self.lengths[slot] = 0
        self.last_token[slot] = 0

    def generate(self, prompt, max_new_tokens=None, eos_id=None):
        """Synchronous single-sequence convenience (tests/probes): the
        greedy continuation of ``prompt``, stopping at ``eos_id`` or
        ``max_new_tokens``, as a list of ids (EOS excluded)."""
        eos = self.spec.eos_id if eos_id is None else eos_id
        slot, first = self.admit(prompt)
        # prefill already produced one token; each further step can
        # write one more K/V row, so cap+1 tokens total fit the slot
        cap = self.capacity_left(slot)
        limit = cap + 1 if max_new_tokens is None \
            else min(int(max_new_tokens), cap + 1)
        tokens = [first]
        try:
            while tokens[-1] != eos and len(tokens) < limit:
                tokens.append(self.step()[slot])
        finally:
            self.retire(slot)
        if tokens and tokens[-1] == eos:
            tokens = tokens[:-1]
        return tokens


class _GenRequest:
    __slots__ = ("prompt", "max_new", "explicit_budget", "eos_id",
                 "future", "deadline", "t_submit", "tokens", "slot",
                 "session_index", "t_last")

    def __init__(self, prompt, max_new, explicit_budget, eos_id,
                 deadline):
        self.prompt = prompt
        self.max_new = max_new
        # True when the CALLER asked for max_new tokens (placement
        # must find a session able to serve them all); False when the
        # budget is the implicit "as much as fits" cap, which any
        # fitting session satisfies by definition
        self.explicit_budget = explicit_budget
        self.eos_id = eos_id  # None until placement picks a session
        self.future = Future()
        self.deadline = deadline  # absolute time.monotonic() or None
        self.t_submit = time.perf_counter()
        self.tokens = []
        self.slot = None
        self.session_index = None
        self.t_last = None


class GenerationScheduler:
    """Continuous-batching front door over one or more
    :class:`GenerationSession` replicas.

    ``submit(prompt) -> Future`` resolves to the generated ids as an
    int64 array (greedy continuation, EOS excluded). The dispatcher
    thread interleaves two moves forever: admit queued requests into
    free cache slots (prefill), and run one decode step for every
    session with active slots. Sequences finish (EOS / token budget /
    deadline) and retire slot-by-slot — co-resident sequences never
    stall or flush for an admit or retire.

    Admission reuses the MicroBatcher discipline: bounded queue
    (``submit`` blocks, or raises :class:`ServingOverloadError` with a
    ``timeout``), queue-wait EWMA shedding when a deadline budget is
    already hopeless, expired deadlines resolved with
    :class:`ServingDeadlineError` before touching a device; a deadline
    that expires MID-generation retires the slot and resolves the
    Future with ServingDeadlineError (stateful requests hold a slot —
    letting them linger past their budget starves admission).

    With ``breaker_failures`` (default: the
    ``serving_breaker_failures`` flag; 0 = off) each session gets a
    :class:`ReplicaBreaker`: a failing session's active requests fail
    over is impossible (their cache state died with the session), so
    they resolve exceptionally, the session is quarantined out of
    admission, and a cooldown-gated trial prefill re-admits it.

    ``drain()`` stops admission and serves everything accepted;
    ``close()`` is the bounded fast exit. ``swap_weights(params)``
    installs new values between decode steps (see method docs).
    """

    def __init__(self, sessions, max_queue=256, deadline_ms=None,
                 breaker_failures=None, breaker_cooldown_ms=None,
                 autostart=True):
        if isinstance(sessions, GenerationSession):
            sessions = [sessions]
        if not sessions:
            raise ValueError("need at least one GenerationSession")
        self.sessions = list(sessions)
        self._q = queue.Queue(maxsize=max_queue)
        # dispatcher-local order-preserving buffer: items parked when
        # no slot is free right now, and re-queue overflow from the
        # deadline sweep (consumed before the queue)
        self._pending = collections.deque()
        # True while some waiting item MAY carry a deadline — gates
        # the per-tick expiry sweep, which would otherwise rotate the
        # whole bounded queue on every decode step for nothing
        self._has_deadlines = False
        self._closed = False
        self._thread = None
        self._wait_ewma = 0.0
        self._active = {}   # (session_index, slot) -> _GenRequest
        self._sched_id = next(_SCHED_SEQ)
        if deadline_ms is None:
            deadline_ms = _config.get_flag("serving_deadline_ms")
        self.default_deadline_ms = deadline_ms
        if breaker_failures is None:
            breaker_failures = _config.get_flag(
                "serving_breaker_failures")
        if breaker_cooldown_ms is None:
            breaker_cooldown_ms = _config.get_flag(
                "serving_breaker_cooldown_ms")
        if breaker_failures:
            self._breakers = [
                ReplicaBreaker(i, breaker_failures,
                               float(breaker_cooldown_ms) / 1e3,
                               label="gen%d:%d" % (self._sched_id, i))
                for i in range(len(self.sessions))]
        else:
            self._breakers = None
        self._swap_lock = threading.Lock()
        self._pending_swap = None  # (params, Future)
        self._weights_version = 0
        if autostart:
            self.start()

    # -- lifecycle -------------------------------------------------------
    def start(self):
        if self._closed:
            raise RuntimeError("scheduler is closed")
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="generation-scheduler",
                                            daemon=True)
            self._thread.start()
        return self

    @property
    def weights_version(self):
        return self._weights_version

    def session_health(self):
        if self._breakers is None:
            return ["closed"] * len(self.sessions)
        return [b.state for b in self._breakers]

    # -- admission -------------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               deadline_ms=None, timeout=None):
        """Enqueue one prompt; returns a Future of its generated ids.

        ``max_new_tokens`` is capped by the slot capacity left after
        the prompt (cache bucket / position table). ``deadline_ms``
        (default: the scheduler's ``deadline_ms``, itself defaulting
        to the ``serving_deadline_ms`` flag; 0/None = none) bounds the
        WHOLE generation. ``timeout``: seconds to wait on a full
        queue before :class:`ServingOverloadError`."""
        if self._closed:
            raise RuntimeError("scheduler is closed")
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        # the prompt must fit SOME session's buckets (placement later
        # routes it only to sessions that can take it); the decode
        # budget cap comes from the most permissive fitting session
        fitting = [s for s in self.sessions
                   if s.prompt_bucket(prompt.size) is not None]
        if not fitting:
            raise ValueError(
                "prompt length %d exceeds every session's largest "
                "prompt bucket (max %d)"
                % (prompt.size,
                   max(s.spec.prompt_buckets[-1]
                       for s in self.sessions)))
        cap = max(s.max_pos for s in fitting) - prompt.size + 1
        if cap < 1:
            raise ValueError(
                "prompt length %d leaves no decode capacity in any "
                "session's cache bucket" % prompt.size)
        explicit = max_new_tokens is not None
        max_new = cap if not explicit else min(int(max_new_tokens), cap)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline = None
        if deadline_ms:  # 0/None = no deadline, the PR-5 contract
            budget = float(deadline_ms) / 1e3
            if budget < 0:
                _sres.DEADLINE_EXCEEDED.inc()
                raise ServingDeadlineError(
                    "deadline budget %.1f ms already spent"
                    % float(deadline_ms))
            projected = self._wait_ewma * (1.0 + self._q.qsize())
            if projected > budget:
                # same geometric decay as the batcher: sheds must not
                # latch the estimate high on an idle queue
                self._wait_ewma *= (1.0 - _WAIT_ALPHA)
                _sres.SHED.inc()
                raise ServingOverloadError(
                    "shed: projected admission wait %.1f ms exceeds "
                    "the %.1f ms deadline budget"
                    % (projected * 1e3, budget * 1e3))
            deadline = time.monotonic() + budget
        item = _GenRequest(prompt, max_new, explicit, eos_id, deadline)
        try:
            self._q.put(item, block=True, timeout=timeout)
        except queue.Full:
            _sres.SHED.inc()
            raise ServingOverloadError(
                "generation queue full (%d pending)"
                % self._q.qsize()) from None
        if deadline is not None:
            # AFTER the put: the sweep recomputes the flag from queue
            # content, so this order can never strand a deadline item
            # behind a cleared flag
            self._has_deadlines = True
        if self._closed and self._thread is None:
            # raced a close()/drain() past its leftover sweep (the
            # batcher's shutdown race, same resolution: fail OUR
            # future idempotently and refuse the submit)
            _resolve(item.future,
                     exception=RuntimeError("scheduler closed"))
            raise RuntimeError("scheduler is closed")
        return item.future

    # -- dispatcher ------------------------------------------------------
    def _next_item(self, block):
        """Next request to place: the parked buffer first (preserves
        order), then the queue. None when nothing is waiting."""
        if self._pending:
            return self._pending.popleft()
        try:
            if block:
                return self._q.get(timeout=0.05)
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def _fits(self, sess, item):
        """Can ``sess`` serve this request IN FULL — prompt bucket and
        enough cache capacity for the promised token budget? Placement
        on a smaller-cache session would silently retire the sequence
        early with reason 'capacity', under-delivering the budget
        submit() accepted. An implicit ("as much as fits") budget is
        satisfied by ANY fitting session — requiring the largest
        session's cap would strand idle smaller replicas."""
        n = item.prompt.size
        need = item.max_new if item.explicit_budget else 1
        return sess.prompt_bucket(n) is not None and \
            sess.max_pos - n + 1 >= need

    def _eligible_session(self, item, claim=False):
        """Index of a session that can take this request NOW
        (free slot + fitting bucket/capacity + breaker closed, or a
        cooldown-elapsed trial when nothing fitting is closed), or
        None. The half_open transition — a trial admission is the
        probe — fires only with ``claim=True``, i.e. when an actual
        request is about to be admitted; a capacity poll must not
        burn a breaker's cooldown with no trial to run."""
        candidates = [i for i, s in enumerate(self.sessions)
                      if s.free_slots() and self._fits(s, item)]
        if not candidates:
            return None
        if self._breakers is None:
            return candidates[0]
        closed = [i for i in candidates
                  if self._breakers[i].state == "closed"]
        if closed:
            return closed[0]
        now = time.monotonic()
        for i in candidates:
            breaker = self._breakers[i]
            if breaker.state == "half_open" or \
                    breaker.ready_to_probe(now):
                if claim:
                    breaker.to_half_open()
                return i
        return None

    def _dispatchable_later(self, item):
        """True when some session fitting this request is healthy
        (or trial-ready) but merely out of free slots — a retiring
        sequence will make room, so the request should wait."""
        for i, s in enumerate(self.sessions):
            if not self._fits(s, item):
                continue
            breaker = self._breakers[i] if self._breakers else None
            if breaker is None or \
                    breaker.state in ("closed", "half_open") or \
                    breaker.ready_to_probe():
                return True
        return False

    def _expire(self, item, where):
        _sres.DEADLINE_EXCEEDED.inc()
        _resolve(item.future, exception=ServingDeadlineError(
            "deadline expired after %.1f ms %s"
            % ((time.perf_counter() - item.t_submit) * 1e3, where)))

    def _expire_queued(self):
        """Resolve expired deadlines for requests still waiting — even
        while every slot is busy. The batcher drops expired items at
        every dispatch tick; a slot-starved stretch must not suspend
        that contract and leave a doomed caller blocked until some
        unrelated sequence retires. Gated by ``_has_deadlines`` so a
        deadline-free workload never pays the queue rotation."""
        if not self._has_deadlines:
            return
        now = time.monotonic()
        remaining = False
        keep = collections.deque()
        while self._pending:
            item = self._pending.popleft()
            if item is not _STOP and item.deadline is not None \
                    and now >= item.deadline:
                self._expire(item, "in queue")
            else:
                if item is not _STOP and item.deadline is not None:
                    remaining = True
                keep.append(item)
        self._pending = keep
        for _ in range(self._q.qsize()):
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP and item.deadline is not None \
                    and now >= item.deadline:
                self._expire(item, "in queue")
            else:
                if item is not _STOP and item.deadline is not None:
                    remaining = True
                try:
                    self._q.put_nowait(item)
                except queue.Full:
                    # a racing submit took the freed capacity: the
                    # parked buffer keeps the item dispatchable
                    self._pending.append(item)
        # recomputed from content — a submit landing mid-sweep re-arms
        # the flag itself after its put
        self._has_deadlines = remaining

    def _place(self, item):
        """Admit ``item`` somewhere, park it for later, or resolve it.
        Returns False when the item was parked (no capacity right now
        — the caller should stop pulling from the queue)."""
        if item.deadline is not None and \
                time.monotonic() >= item.deadline:
            self._expire(item, "in queue")
            return True
        si = self._eligible_session(item, claim=True)
        if si is None:
            if self._dispatchable_later(item):
                self._pending.appendleft(item)
                return False
            # every fitting session is quarantined with its cooldown
            # still running: fail explicitly rather than wedging the
            # request in a queue nothing drains (stateful requests
            # can't fail over mid-flight, so honesty beats hope)
            _resolve(item.future, exception=ServingUnavailableError(
                "no healthy generation session for this prompt"))
            return True
        self._admit_item(item, si)
        return True

    def _admit_item(self, item, si):
        wait = time.perf_counter() - item.t_submit
        self._wait_ewma += _WAIT_ALPHA * (wait - self._wait_ewma)
        sess = self.sessions[si]
        breaker = self._breakers[si] if self._breakers else None
        try:
            slot, first = sess.admit(item.prompt)
        except ValueError as exc:
            # a client-shaped prompt (bucket/length) is the request's
            # fault, not the session's — it must not charge the
            # breaker and quarantine a healthy session
            _resolve(item.future, exception=exc)
            return
        except Exception as exc:
            if breaker is not None:
                breaker.record_failure()
            _resolve(item.future, exception=exc)
            return
        if breaker is not None:
            breaker.record_success()
        if item.eos_id is None:
            item.eos_id = sess.spec.eos_id
        _REQUESTS.inc()
        _TOKENS.inc()
        now_pc = time.perf_counter()
        _TTFT_SECONDS.observe(now_pc - item.t_submit)
        item.t_last = now_pc
        item.slot = slot
        item.session_index = si
        item.tokens.append(first)
        self._active[(si, slot)] = item
        self._update_occupancy()
        self._finish_if_done(item)  # EOS/budget can end it at token 1

    def _finish_if_done(self, item):
        """Retire/resolve when EOS, budget, capacity, or deadline ends
        the sequence. Returns True when the request left its slot."""
        sess = self.sessions[item.session_index]
        reason = None
        if item.tokens and item.tokens[-1] == item.eos_id:
            item.tokens.pop()
            reason = "eos"
        elif len(item.tokens) >= item.max_new:
            reason = "max_tokens"
        elif sess.capacity_left(item.slot) <= 0:
            reason = "capacity"
        elif item.deadline is not None and \
                time.monotonic() >= item.deadline:
            reason = "deadline"
        if reason is None:
            return False
        sess.retire(item.slot)
        del self._active[(item.session_index, item.slot)]
        _RETIRED.labels(reason=reason).inc()
        if reason == "deadline":
            _sres.DEADLINE_EXCEEDED.inc()
            _resolve(item.future, exception=ServingDeadlineError(
                "deadline expired mid-generation after %d tokens"
                % len(item.tokens)))
        else:
            _REQUEST_SECONDS.observe(time.perf_counter()
                                     - item.t_submit)
            _resolve(item.future,
                     result=np.asarray(item.tokens, np.int64))
        self._update_occupancy()
        return True

    def _step_all(self):
        for si, sess in enumerate(self.sessions):
            mine = [(slot, it) for (s_i, slot), it
                    in list(self._active.items()) if s_i == si]
            if not mine:
                continue
            breaker = self._breakers[si] if self._breakers else None
            try:
                _faults.fire_point("generation_step_fail", index=si)
                toks = sess.step()
            except Exception as exc:
                # a session's cache state is unrecoverable mid-flight:
                # its requests resolve exceptionally and the breaker
                # (when armed) quarantines the session out of
                # admission until a trial prefill succeeds
                if breaker is not None:
                    breaker.record_failure()
                _log.structured("generation_step_failed", session=si,
                                error=repr(exc),
                                requests=len(mine))
                for slot, it in mine:
                    sess.retire(slot)
                    self._active.pop((si, slot), None)
                    _RETIRED.labels(reason="error").inc()
                    _resolve(it.future, exception=exc)
                self._update_occupancy()
                continue
            if breaker is not None:
                breaker.record_success()
            _STEPS.inc()
            _TOKENS.inc(len(mine))
            now_pc = time.perf_counter()
            for slot, it in mine:
                it.tokens.append(toks[slot])
                _INTER_TOKEN_SECONDS.observe(now_pc - it.t_last)
                it.t_last = now_pc
                self._finish_if_done(it)

    def _update_occupancy(self):
        total = sum(s.spec.slots for s in self.sessions)
        _OCCUPANCY.labels(scheduler="gen%d" % self._sched_id).set(
            len(self._active) / float(total))

    def _apply_pending_swap(self):
        with self._swap_lock:
            pending, self._pending_swap = self._pending_swap, None
        if pending is None:
            return
        params, future = pending
        try:
            scopes = []
            for sess in self.sessions:
                if sess.scope not in scopes:
                    scopes.append(sess.scope)
            cache_names = {name for s in self.sessions
                           for name, _, _ in s.spec.cache_vars}
            # phase 1: validate EVERY scope before mutating ANY — a
            # rejection on the second scope must not leave the first
            # already serving the rejected weights (torn swap)
            for scope in scopes:
                for name, val in params.items():
                    if name in cache_names:
                        raise ValueError(
                            "refusing to overwrite cache variable %r"
                            % name)
                    cur = scope.find_var(name)
                    if cur is None:
                        raise ValueError(
                            "swap names unknown variable %r" % name)
                    val = np.asarray(val)
                    # metadata-only checks: materializing live device
                    # params on host here would stall the decode loop
                    # for a full model D2H copy per swap
                    cur_shape = tuple(np.shape(cur))
                    cur_dtype = np.dtype(cur.dtype) \
                        if hasattr(cur, "dtype") \
                        else np.asarray(cur).dtype
                    if tuple(val.shape) != cur_shape or \
                            val.dtype != cur_dtype:
                        raise ValueError(
                            "signature mismatch on %r: push %s/%s vs "
                            "live %s/%s"
                            % (name, val.shape, val.dtype,
                               cur_shape, cur_dtype))
            # phase 2: install everywhere (pure pointer installs —
            # nothing here can raise and tear the fleet)
            for scope in scopes:
                for name, val in params.items():
                    scope.set_var(name, np.asarray(val))
            self._weights_version += 1
            _log.structured("generation_weights_swapped",
                            version=self._weights_version,
                            params=len(params))
            _resolve(future, result=self._weights_version)
        except Exception as exc:
            _resolve(future, exception=exc)

    def swap_weights(self, params, timeout=30.0):
        """Install new parameter values (``{name: array}``) on every
        session's scope BETWEEN decode steps — the hot-swap story for
        stateful serving. The flip lands on a step boundary (the
        dispatcher applies it before its next admit/step), so no
        forward pass mixes versions; sequences already mid-generation
        continue on the new weights, which is the documented semantic
        for session state (their KV cache keeps the old weights'
        values — retire-and-retry callers who need strict isolation).
        Cache variables are refused; name/shape/dtype mismatches
        reject the push. Returns the new weights version."""
        if self._closed:
            raise RuntimeError("scheduler is closed")
        future = Future()
        with self._swap_lock:
            if self._pending_swap is not None:
                raise RuntimeError("a weight swap is already pending")
            self._pending_swap = (dict(params), future)
        if self._thread is None:
            self._apply_pending_swap()
        try:
            return future.result(timeout=timeout)
        except FutureTimeoutError:
            with self._swap_lock:
                if self._pending_swap is not None and \
                        self._pending_swap[1] is future:
                    # still queued: cancel it so the "failed" push can
                    # never land silently later, and a retry isn't
                    # blocked by a phantom pending swap
                    self._pending_swap = None
                    raise RuntimeError(
                        "weight swap not applied within %.0fs — "
                        "cancelled" % timeout) from None
            # the dispatcher picked it up mid-wait: the install is a
            # bounded pointer flip, give it a moment to land
            return future.result(timeout=5.0)

    def _serve_out(self):
        """Post-stop epilogue, on the dispatcher thread: finish every
        active slot AND place-and-serve everything still waiting —
        including submits that raced the stop marker into the queue
        (a timed-out close()/drain() join leaves this thread sole
        owner of the queues, so an unserved straggler here would be a
        Future nothing ever resolves). Waiting items co-batch into
        free slots like live traffic."""
        while True:
            self._apply_pending_swap()
            if self._active:
                self._step_all()
                continue
            item = self._next_item(block=False)
            if item is None:
                return
            if item is _STOP:
                continue
            if not self._place(item) and not self._active:
                # unplaceable with nothing in flight (external slot
                # holders): resolve rather than spinning forever
                parked = self._pending.popleft()
                _resolve(parked.future,
                         exception=ServingUnavailableError(
                             "scheduler stopped before the request "
                             "could be placed"))

    def _loop(self):
        while True:
            self._apply_pending_swap()
            if self._active:
                self._expire_queued()
                got_stop = self._fill_slots()
                self._step_all()
                if got_stop:
                    self._serve_out()
                    return
            else:
                item = self._next_item(block=True)
                if item is None:
                    if self._closed:
                        return
                    continue
                if item is _STOP:
                    self._serve_out()  # stragglers behind the marker
                    return
                if not self._place(item):
                    # parked with nothing active: only possible while
                    # every fitting session's slots are held outside
                    # this scheduler — back off instead of spinning
                    time.sleep(0.02)

    def _fill_slots(self):
        """Admit waiting requests into free slots without blocking.
        Returns True when the stop marker was consumed."""
        while True:
            item = self._next_item(block=False)
            if item is None:
                return False
            if item is _STOP:
                return True
            if not self._place(item):
                return False  # head parked: no capacity this tick

    # -- shutdown --------------------------------------------------------
    def _stop_dispatcher(self, timeout):
        self._closed = True
        if self._thread is not None:
            try:
                self._q.put_nowait(_STOP)
            except queue.Full:
                pass
            self._thread.join(timeout)
            if self._thread.is_alive():
                # the dispatcher is still finishing in-flight
                # generations past the bounded wait: it OWNS the
                # queues (sweeping them from under a live thread
                # races its every tick) and will serve what it holds
                # and exit on closed. Leave everything to it.
                return []
            self._thread = None
        leftovers = [item for item in self._pending if item is not _STOP]
        self._pending.clear()
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                leftovers.append(item)
        return leftovers

    def drain(self, timeout=None):
        """Graceful drain: stop admission, generate every accepted
        request to completion — in-flight slots AND waiting submits
        (parked or racing the stop marker; served synchronously here,
        slot by slot) — then stop. Every accepted Future resolves."""
        leftovers = self._stop_dispatcher(timeout)
        if self._thread is not None:
            # bounded join expired with the dispatcher still serving:
            # it finishes and resolves everything it holds on its own
            # thread (_serve_out) — two threads must not step the
            # same sessions
            return
        # dispatcher never started or was wedged: serve the remainder
        # here, co-batching waiting requests into free slots (placing
        # one-at-a-time would run each generation solo and forfeit
        # the batching this layer exists for)
        self._pending.extend(leftovers)
        while self._pending or self._active:
            progressed = False
            while self._pending:
                if not self._place(self._pending.popleft()):
                    break  # head parked again: a step must free slots
                progressed = True
            if self._active:
                self._step_all()
            elif not progressed and self._pending:
                # unplaceable with nothing in flight (external slot
                # holders): resolve rather than spinning forever
                parked = self._pending.popleft()
                _resolve(parked.future,
                         exception=ServingUnavailableError(
                             "drain: no session could take the "
                             "request"))

    def close(self, timeout=5.0):
        """Fast exit: a live dispatcher serves out everything it owns
        (active slots AND accepted submits) before exiting — past the
        bounded join it keeps doing so on its own thread — so no
        accepted Future is ever left hanging; with no dispatcher
        running, queued requests are failed instead."""
        for item in self._stop_dispatcher(timeout):
            _resolve(item.future,
                     exception=RuntimeError("scheduler closed"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
