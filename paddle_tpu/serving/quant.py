"""Post-training int8 weight quantization for exported inference models.

The measured lever (PROFILE.md round 5): int8 matmul runs 1.71x bf16
throughput on a v5e MXU and halves weight bytes — the right win for
*serving*, where weights are frozen and per-channel scales recover
almost all f32 accuracy. This pass is the serving-side wiring of that
probe: ``io.save_inference_model(..., quantize="int8")`` rewrites the
exported ``params.npz`` so matmul/conv weights are stored as int8 plus
per-output-channel symmetric scales (a ``quant.json`` sidecar). Two
load modes consume the artifact:

* **dequantize-at-load** (default, unchanged since PR 2):
  ``io.load_inference_model`` rebuilds f32 weights in the scope, so
  every consumer (InferenceEngine, ServingEngine, the C API bridge, a
  merged single-file model) reads a quantized artifact with no code
  changes.
* **int8 COMPUTE** (``serving_quant_compute`` flag): the int8 weights
  stay int8 on device and the consuming matmul/conv runs int8 x int8
  accumulated in int32 on the MXU, with the stored per-output-channel
  scale applied in one fused f32 epilogue (ops/quant_ops.py — the op
  bodies, the Pallas fused dequant-matmul kernel for the decode hot
  path, and the numerics contract). :func:`install_quant_compute`
  arms an artifact load (``ServingEngine`` reads the flag and passes
  ``quant_compute=True`` to ``load_inference_model``; the f32 copy is
  never materialized); :func:`arm_quant_compute` arms a live
  ``GenerationSession`` scope, quantizing in place. Both tag the
  program (``program._quant_compute``) so the executor keys its
  compile cache and routes the tagged ops; the per-var scales live in
  the scope as ``<name>@quant.scale`` sidecar vars.

Compute arming is STRICTER than storage quantization
(:func:`select_compute_vars` vs :func:`select_quant_vars`): the scaled
axis must be the contraction *output* in every consumer, so 2-D
weights only for mul/matmul, ``y_num_col_dims == 1``, no
``transpose_Y``, 4-D axis-0 filters for conv2d. Storage-quantized vars
a compute arm can't serve are dequantized at install exactly as the
default path would — an artifact never half-loads.

Scope of the storage pass — weight-only, conservative:

* only float32 ``Parameter`` tensors consumed exclusively through the
  weight slot of a quantizable op (``mul``/``matmul`` rhs, ``conv2d``
  filter) are quantized; biases, BN/LN scales, embeddings stay f32.
* per-OUTPUT-channel symmetric scales (``scale_c = max|w_c| / 127``):
  axis 1 for ``[in, out]`` matmul weights, axis 0 for
  ``[out, in, kh, kw]`` conv filters.
* a fallback list of numerically sensitive ops (softmax, layer_norm,
  batch_norm, losses) — any parameter a fallback op touches is left in
  high precision, mirroring the mixed-precision black list.
"""

import json
import os

import numpy as np

__all__ = ["quantize_array", "dequantize_array", "select_quant_vars",
           "select_compute_vars", "quantize_model_dir", "load_quant_meta",
           "maybe_dequantize", "install_quant_compute",
           "arm_quant_compute", "scale_var_name",
           "QUANT_OPS", "DEFAULT_FALLBACK_OPS", "QUANT_META_FILE"]

QUANT_META_FILE = "quant.json"

# op type -> (weight input slot, per-output-channel axis of that weight)
QUANT_OPS = {
    "mul": ("Y", -1),
    "matmul": ("Y", -1),
    "conv2d": ("Filter", 0),
}

# Parameters consumed by any of these stay high precision (the serving
# analog of the executor's AMP_BLACK list: sensitive reductions and
# normalizers whose tiny affine params are not worth 8 bits).
DEFAULT_FALLBACK_OPS = frozenset({
    "softmax", "layer_norm", "batch_norm", "lookup_table",
    "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
})


def quantize_array(w, axis):
    """Symmetric per-channel int8: returns ``(q int8, scales f32)`` with
    ``scales.shape == (w.shape[axis],)`` and ``w ~= q * scales`` along
    ``axis`` (max abs error <= scale/2 per element)."""
    w = np.asarray(w, dtype=np.float32)
    axis = axis % w.ndim
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    amax = np.max(np.abs(w), axis=reduce_axes) if reduce_axes \
        else np.abs(w)
    scales = (amax / 127.0).astype(np.float32)
    scales = np.where(scales == 0.0, np.float32(1.0), scales)
    shape = [1] * w.ndim
    shape[axis] = w.shape[axis]
    q = np.clip(np.rint(w / scales.reshape(shape)), -127, 127) \
        .astype(np.int8)
    return q, scales


def dequantize_array(q, scales, axis):
    """Inverse of :func:`quantize_array` (up to rounding): f32 array."""
    q = np.asarray(q)
    scales = np.asarray(scales, dtype=np.float32)
    axis = axis % q.ndim
    shape = [1] * q.ndim
    shape[axis] = q.shape[axis]
    return q.astype(np.float32) * scales.reshape(shape)


def select_quant_vars(program, fallback_ops=DEFAULT_FALLBACK_OPS):
    """Map parameter name -> per-output-channel axis for every parameter
    of ``program`` that is safe to quantize (see module docstring)."""
    from ..core.framework import Parameter

    block = program.global_block()
    consumers = {}  # param name -> [(op_type, slot)]
    for op in block.ops:
        for slot, names in op.inputs.items():
            for n in names:
                v = block.var_or_none(n)
                if isinstance(v, Parameter):
                    consumers.setdefault(n, []).append((op.type, slot))

    out = {}
    for name, uses in consumers.items():
        var = block.var(name)
        if str(np.dtype(var.dtype)) != "float32" or var.shape is None \
                or len(var.shape) < 2:
            continue
        if any(op_type in fallback_ops for op_type, _ in uses):
            continue
        axes = set()
        ok = True
        for op_type, slot in uses:
            spec = QUANT_OPS.get(op_type)
            if spec is None or spec[0] != slot:
                ok = False
                break
            axes.add(spec[1] % len(var.shape))
        if ok and len(axes) == 1:
            out[name] = axes.pop()
    return out


def quantize_model_dir(dirname, program=None,
                       fallback_ops=DEFAULT_FALLBACK_OPS, dtype="int8"):
    """Rewrite an exported inference-model dir in place: quantizable
    params in ``params.npz`` become int8 and ``quant.json`` records the
    per-var scales. Returns the list of quantized var names."""
    if dtype not in ("int8", True):
        raise ValueError("unsupported quantize mode %r (only 'int8')"
                         % (dtype,))
    if program is None:
        from ..core.serialization import program_from_dict
        with open(os.path.join(dirname, "__model__")) as f:
            program = program_from_dict(json.load(f)["program"])
    targets = select_quant_vars(program, fallback_ops=fallback_ops)

    npz_path = os.path.join(dirname, "params.npz")
    meta_path = os.path.join(dirname, "params.meta.json")
    with np.load(npz_path) as data:
        arrays = {k: data[k] for k in data.files}
    with open(meta_path) as f:
        meta = json.load(f)

    quantized = {}
    for key, name in meta.items():
        axis = targets.get(name)
        if axis is None or key not in arrays:
            continue
        q, scales = quantize_array(arrays[key], axis)
        arrays[key] = q
        quantized[name] = {"axis": int(axis),
                           "scales": [float(s) for s in scales]}
    np.savez(npz_path[:-len(".npz")], **arrays)
    with open(os.path.join(dirname, QUANT_META_FILE), "w") as f:
        json.dump({"version": 1, "dtype": "int8", "vars": quantized}, f)
    # post-hoc quantization of an already-manifested artifact must
    # refresh the digests (params.npz was rewritten in place) or every
    # subsequent load fails integrity verification; inside
    # save_inference_model there is no manifest yet — it lands after
    from .. import io as _io
    if os.path.exists(os.path.join(dirname, "manifest.json")):
        _io.write_artifact_manifest(dirname)
    return sorted(quantized)


def load_quant_meta(dirname):
    """The dir's quant.json dict, or None when not quantized."""
    path = os.path.join(dirname, QUANT_META_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def maybe_dequantize(dirname, scope):
    """If ``dirname`` carries quant.json, replace each quantized var in
    ``scope`` with its dequantized f32 array (transparent load path).
    Returns the list of dequantized names."""
    meta = load_quant_meta(dirname)
    if meta is None:
        return []
    done = []
    for name, info in meta["vars"].items():
        q = scope.find_var(name)
        if q is None:
            continue
        scope.set_var(name, dequantize_array(
            np.asarray(q), info["scales"], info["axis"]))
        done.append(name)
    return done


# ---------------------------------------------------------------------
# int8 COMPUTE arming (ops/quant_ops.py runs the armed ops)
# ---------------------------------------------------------------------

def scale_var_name(name):
    """Scope name of the scale sidecar var for weight ``name``."""
    from ..ops import quant_ops as _qops
    return _qops.scale_var_name(name)


def select_compute_vars(program, fallback_ops=DEFAULT_FALLBACK_OPS):
    """Subset of :func:`select_quant_vars` the int8 COMPUTE path can
    serve. Beyond storage safety, every consumer must keep the scaled
    (output-channel) axis OUT of the contraction: 2-D weights only for
    mul/matmul, ``y_num_col_dims == 1`` for mul, no ``transpose_Y`` for
    matmul (it would contract over the scaled axis), 4-D axis-0 filters
    for conv2d."""
    targets = select_quant_vars(program, fallback_ops=fallback_ops)
    if not targets:
        return {}
    block = program.global_block()
    bad = set()
    for op in block.ops:
        spec = QUANT_OPS.get(op.type)
        if spec is None:
            continue
        slot = spec[0]
        for n in op.inputs.get(slot, []):
            if n not in targets:
                continue
            nd = len(block.var(n).shape)
            if op.type in ("mul", "matmul") and nd != 2:
                bad.add(n)
            elif op.type == "mul" and \
                    op.attrs.get("y_num_col_dims", 1) != 1:
                bad.add(n)
            elif op.type == "matmul" and \
                    op.attrs.get("transpose_Y", False):
                bad.add(n)
            elif op.type == "conv2d" and nd != 4:
                bad.add(n)
    return {n: a for n, a in targets.items() if n not in bad}


def _tag_program(program, vars_, pallas):
    """Attach the executor-facing compute tag. The tag keys the compile
    cache (``key`` is hashable and order-stable), so re-arming with the
    same var set reuses the compiled step."""
    vars_ = dict(vars_)
    program._quant_compute = {
        "vars": vars_,
        "pallas": bool(pallas),
        "key": (tuple(sorted(vars_.items())), bool(pallas)),
    }


def install_quant_compute(dirname, program, scope, pallas=None):
    """Artifact-load arming: keep the int8 weights that the compute
    path can serve AS int8 in ``scope`` (their scales become
    ``<name>@quant.scale`` sidecar vars — the f32 copy is never
    materialized), dequantize the rest exactly like the default load,
    and tag ``program``. Returns the list of compute-armed names."""
    meta = load_quant_meta(dirname)
    if meta is None:
        return []
    if pallas is None:
        from .. import config as _config
        pallas = bool(_config.get_flag("quant_pallas"))
    compute = select_compute_vars(program)
    armed = {}
    for name, info in meta["vars"].items():
        q = scope.find_var(name)
        if q is None:
            continue
        axis = compute.get(name)
        if axis is not None and int(info["axis"]) == axis:
            scope.set_var(name, np.asarray(q))
            scope.set_var(scale_var_name(name),
                          np.asarray(info["scales"], dtype=np.float32))
            armed[name] = axis
        else:
            scope.set_var(name, dequantize_array(
                np.asarray(q), info["scales"], info["axis"]))
    if armed:
        _tag_program(program, armed, pallas)
    return sorted(armed)


def arm_quant_compute(programs, scope, fallback_ops=DEFAULT_FALLBACK_OPS,
                      pallas=None):
    """Live-session arming: quantize ``scope``'s weights in place and
    tag every program in ``programs`` that consumes them. A var is
    armed only when EVERY program either doesn't reference it or
    selects it with the same axis — programs share the scope, so a
    single non-quantizable consumer anywhere keeps the var f32.
    Idempotent: an already-int8 var with its scale sidecar present is
    tagged without re-quantizing (re-arming after ``_rebuild`` or for
    a draft session sharing the target scope). Returns the sorted list
    of armed names."""
    programs = [p for p in programs if p is not None]
    if not programs:
        return []
    if pallas is None:
        from .. import config as _config
        pallas = bool(_config.get_flag("quant_pallas"))
    selections = [select_compute_vars(p, fallback_ops=fallback_ops)
                  for p in programs]
    referenced = []
    for p in programs:
        names = set()
        for op in p.global_block().ops:
            for lst in op.inputs.values():
                names.update(lst)
        referenced.append(names)
    candidates = {}
    for sel in selections:
        candidates.update(sel)
    armed = {}
    for name, axis in candidates.items():
        if any(name in refs and sel.get(name) != axis
               for refs, sel in zip(referenced, selections)):
            continue
        w = scope.find_var(name)
        if w is None:
            continue
        w = np.asarray(w)
        sname = scale_var_name(name)
        if w.dtype == np.int8:
            if scope.find_var(sname) is None:
                continue  # foreign int8 without scales: not ours
        else:
            q, scales = quantize_array(w, axis)
            scope.set_var(name, q)
            scope.set_var(sname, scales)
        armed[name] = axis
    if armed:
        for p, sel in zip(programs, selections):
            tag = {n: a for n, a in sel.items() if n in armed}
            if tag:
                _tag_program(p, tag, pallas)
    return sorted(armed)
