"""Multi-model fleet paging: residency as first-class fleet state.

PR 18 left every fleet member serving exactly one model; this module
is ROADMAP item 4's residual — N models share a fleet whose HBM holds
only a hot subset, and a tenant maps to a *model*, not just a quota
row. The paper's pserver lineage (PAPER.md) treats parameter placement
as a runtime concern; here the placed resource is a whole weight set:

* :class:`ModelCatalog` / :class:`ModelSpec` — the fleet's model
  table: every model the fleet may page, its artifact
  (``params_path`` for generation workers, ``model_dir`` for engine
  workers), its weights ``tag`` (the version the journal fence sees),
  its catalog-accounted ``bytes`` (the eviction currency), and the
  tenants it serves. Armed by the ``fleet_models`` flag or the
  router's ``models=`` constructor arg.
* :class:`ModelResidencySet` — the router-side view of ONE member's
  resident models, fenced by the membership generation that reported
  it (a dead incarnation's residency dies with its member row; a
  stale heartbeat's advertisement is ignored exactly like its world
  view). Tracks per-model last-use (the LRU clock) and an in-flight
  pin count — the BlockPool refcount discipline applied to whole
  weight sets: :meth:`ModelResidencySet.lru_victims` can never name a
  pinned model, and the router asserts the invariant again at the
  eviction site.

The router (serving/fleet.py) composes these into the full story:
residency-affinity placement (the least-loaded score gains a
residency term keyed on model id), demand paging through the PR-7
swap gates (``page_in`` verb: manifest-verified staged load ->
canary -> flip, bounded by ``model_page_timeout_ms`` and charged to
the PR-18 spawn-failure budget on wedge), LRU eviction pressure
against ``member_resident_bytes``, and journal replay across a
page-out — a journal whose model was paged out re-pages it on the
target member BEFORE re-drive, so a SIGKILL'd member's in-flight
generations land bit-identically on a peer that didn't hold the
model when the request started.

Fault sites (resilience/faults.py): ``model_page_in_fail`` (worker
side, indexed by model id — the page-in raises before any weight
lands), ``model_page_in_slow`` (worker side, indexed by model id —
arm a callback sleeping past ``model_page_timeout_ms`` to wedge the
page-in), ``model_evict_race`` (router side, indexed by model id,
fired between victim selection and the page-out — arm a callback
that pins the victim to prove eviction re-checks the in-flight
invariant instead of racing it).

Default flags construct none of this: no catalog, no residency rows,
no paging verbs on any frame.
"""

import json
import os
import time

from ..observability import metrics as _metrics

__all__ = ["ModelSpec", "ModelCatalog", "ModelResidencySet",
           "PageInError", "write_weights_manifest",
           "verify_weights_manifest"]

PAGE_INS = _metrics.REGISTRY.counter(
    "paddle_fleet_model_page_ins_total",
    "Demand page-ins by outcome (ok; fail: the member rejected or "
    "errored the staged load; timeout: no reply within "
    "model_page_timeout_ms — charged to the autoscaler's "
    "spawn-failure budget like a wedged spawn)",
    labelnames=("outcome",))
PAGE_IN_MS = _metrics.REGISTRY.histogram(
    "paddle_fleet_model_page_in_ms",
    "Demand page-in latency: router decision -> the target member's "
    "flip committed (manifest-verified staged load + canary + flip)",
    buckets=_metrics.LATENCY_MS_BUCKETS)
EVICTIONS = _metrics.REGISTRY.counter(
    "paddle_fleet_model_evictions_total",
    "Resident models paged out under LRU byte pressure (never a "
    "model with in-flight requests — that is an invariant assert, "
    "not a counter)")
RESIDENCY_HITS = _metrics.REGISTRY.counter(
    "paddle_fleet_model_residency_hits_total",
    "Requests whose model was already resident on a live member at "
    "submit (the affinity steady state)")
RESIDENCY_MISSES = _metrics.REGISTRY.counter(
    "paddle_fleet_model_residency_misses_total",
    "Requests that found no live resident member and triggered (or "
    "waited on) a demand page-in")
MODEL_REQUEST_MS = _metrics.REGISTRY.histogram(
    "paddle_fleet_model_request_ms",
    "Router submit -> resolution, one child per model (the per-model "
    "slice of paddle_fleet_request_ms, same discipline as the "
    "per-tenant family); only populated when the router has a model "
    "catalog", labelnames=("model",),
    buckets=_metrics.LATENCY_MS_BUCKETS)
MODEL_DEADLINE = _metrics.REGISTRY.counter(
    "paddle_fleet_model_deadline_total",
    "Deadline-expired fleet requests attributed to one model (feeds "
    "that model's SLO bad count)", labelnames=("model",))
RESIDENT_BYTES = _metrics.REGISTRY.gauge(
    "paddle_fleet_member_resident_bytes",
    "Catalog-accounted bytes of the member's resident model set "
    "(what member_resident_bytes bounds)", labelnames=("member",))


class PageInError(RuntimeError):
    """A demand page-in failed or wedged: the model could not be made
    resident on any eligible member within the paging budget."""


class ModelSpec:
    """One catalog row: where a model's weights live and what they
    cost. ``params_path`` (an ``.npz`` of {name: array}) feeds
    generation-scheduler members, ``model_dir`` feeds stateless
    engine members — exactly the rolling-deploy artifact split.
    ``tag`` is the weights version the member acks after paging this
    model in (the journal fence sees it); ``nbytes`` is the
    catalog-accounted size the eviction budget charges (defaults to
    the artifact's on-disk size); ``tenants`` names the tenants this
    model serves (the submit-side tenant -> model resolution)."""

    __slots__ = ("model_id", "params_path", "model_dir", "tag",
                 "_nbytes", "tenants")

    def __init__(self, model_id, params_path=None, model_dir=None,
                 tag=None, nbytes=None, tenants=()):
        if params_path is None and model_dir is None:
            raise ValueError(
                "model %r needs params_path or model_dir" % model_id)
        self.model_id = str(model_id)
        self.params_path = (None if params_path is None
                            else str(params_path))
        self.model_dir = None if model_dir is None else str(model_dir)
        self.tag = ("%s@v0" % self.model_id) if tag is None else str(tag)
        self._nbytes = None if nbytes is None else int(nbytes)
        self.tenants = tuple(str(t) for t in (tenants or ()))

    def nbytes(self):
        """Catalog-accounted bytes of this model's weight set — the
        explicit size when given, else the artifact's on-disk size
        (computed once; 0 when the artifact is not stat-able, so an
        unknown size can never fake eviction headroom as pressure)."""
        if self._nbytes is None:
            total = 0
            path = self.params_path or self.model_dir
            try:
                if os.path.isdir(path):
                    for root, _dirs, files in os.walk(path):
                        for f in files:
                            total += os.path.getsize(
                                os.path.join(root, f))
                else:
                    total = os.path.getsize(path)
            except OSError:
                total = 0
            self._nbytes = int(total)
        return self._nbytes

    def doc(self):
        return {"tag": self.tag, "bytes": self.nbytes(),
                "artifact": self.params_path or self.model_dir,
                "tenants": list(self.tenants)}


class ModelCatalog:
    """The fleet's model table: id -> :class:`ModelSpec`, plus the
    tenant -> model resolution ``submit`` uses when the caller names
    a tenant but not a model."""

    def __init__(self, specs):
        self._specs = {}
        self._by_tenant = {}
        for spec in specs:
            if spec.model_id in self._specs:
                raise ValueError("duplicate model id %r"
                                 % spec.model_id)
            self._specs[spec.model_id] = spec
            for tid in spec.tenants:
                if tid in self._by_tenant:
                    raise ValueError(
                        "tenant %r mapped to both %r and %r"
                        % (tid, self._by_tenant[tid], spec.model_id))
                self._by_tenant[tid] = spec.model_id

    @classmethod
    def from_value(cls, value):
        """Build from the ``fleet_models`` flag / constructor shape —
        ``{model id: {"params_path"/"model_dir": ..., "tag": ...,
        "bytes": N, "tenants": (...)}}`` — or pass a ready catalog
        through."""
        if isinstance(value, ModelCatalog):
            return value
        specs = []
        for mid, row in dict(value).items():
            row = dict(row)
            specs.append(ModelSpec(
                mid,
                params_path=row.get("params_path"),
                model_dir=row.get("model_dir"),
                tag=row.get("tag"),
                nbytes=row.get("bytes"),
                tenants=row.get("tenants", ())))
        return cls(specs)

    def get(self, model_id):
        spec = self._specs.get(str(model_id))
        if spec is None:
            raise KeyError("model %r is not in the fleet catalog (%s)"
                           % (model_id, sorted(self._specs)))
        return spec

    def __contains__(self, model_id):
        return str(model_id) in self._specs

    def __len__(self):
        return len(self._specs)

    def ids(self):
        return sorted(self._specs)

    def items(self):
        return sorted(self._specs.items())

    def for_tenant(self, tenant):
        """The model serving ``tenant``, or None when no catalog row
        names it (the request then needs an explicit ``model=``, or
        rides model-less like a pre-catalog fleet)."""
        if tenant is None:
            return None
        return self._by_tenant.get(str(tenant))

    def doc(self):
        return {mid: spec.doc() for mid, spec in self.items()}


class _Resident:
    __slots__ = ("last_use", "nbytes")

    def __init__(self, last_use, nbytes):
        self.last_use = last_use
        self.nbytes = nbytes


class ModelResidencySet:
    """Router-side residency of ONE member, fenced by generation.

    The member advertises its resident model ids on REG and on every
    heartbeat; :meth:`update` replaces the set only when the
    advertisement's generation is current (a stale world view's
    residency claim is as untrustworthy as its membership view — the
    same PR-6 fence, applied to the paged resource). Last-use stamps
    survive an update for retained ids, so the LRU clock is not reset
    by every beat. Pins are the in-flight refcount: a model a request
    is currently dispatched against can NEVER be an eviction victim.

    Not self-locking — every mutation happens under the router's
    membership lock, exactly like the _Member fields beside it."""

    __slots__ = ("models", "pins", "generation")

    def __init__(self):
        self.models = {}      # model id -> _Resident
        self.pins = {}        # model id -> in-flight pin count
        self.generation = None

    def update(self, model_ids, generation, catalog=None, now=None):
        """Replace the resident set from a member advertisement made
        at ``generation``. Byte sizes come from the catalog when it
        knows the model (0 otherwise — foreign models never fake
        pressure)."""
        now = time.monotonic() if now is None else now
        fresh = {}
        for mid in model_ids or ():
            mid = str(mid)
            cur = self.models.get(mid)
            nbytes = (catalog.get(mid).nbytes()
                      if catalog is not None and mid in catalog else 0)
            fresh[mid] = cur if cur is not None \
                else _Resident(now, nbytes)
            fresh[mid].nbytes = nbytes
        self.models = fresh
        self.generation = generation

    def add(self, model_id, nbytes=0, now=None):
        """Record one model as resident NOW (the router's own page-in
        landing, ahead of the member's next advertisement)."""
        mid = str(model_id)
        now = time.monotonic() if now is None else now
        r = self.models.get(mid)
        if r is None:
            self.models[mid] = _Resident(now, int(nbytes))
        else:
            r.last_use = now
            r.nbytes = int(nbytes)

    def resident(self, model_id):
        return str(model_id) in self.models

    def touch(self, model_id, now=None):
        r = self.models.get(str(model_id))
        if r is not None:
            r.last_use = time.monotonic() if now is None else now

    def pin(self, model_id):
        mid = str(model_id)
        self.pins[mid] = self.pins.get(mid, 0) + 1

    def unpin(self, model_id):
        mid = str(model_id)
        n = self.pins.get(mid, 0) - 1
        if n <= 0:
            self.pins.pop(mid, None)
        else:
            self.pins[mid] = n

    def pinned(self, model_id):
        return self.pins.get(str(model_id), 0)

    def drop(self, model_id):
        self.models.pop(str(model_id), None)

    def nbytes(self):
        return sum(r.nbytes for r in self.models.values())

    def lru_victims(self, budget, protect=()):
        """Resident models to evict, LRU-first, until the set fits
        ``budget`` bytes. NEVER a pinned model (in-flight requests),
        never one in ``protect`` (the active model, the model just
        paged in). May return fewer victims than the budget wants —
        pinned residents are simply not evictable, and the caller
        retries pressure after they drain."""
        protect = {str(p) for p in protect}
        over = self.nbytes() - int(budget)
        if over <= 0:
            return []
        victims = []
        for mid, r in sorted(self.models.items(),
                             key=lambda kv: kv[1].last_use):
            if over <= 0:
                break
            if mid in protect or self.pins.get(mid, 0) > 0:
                continue
            victims.append(mid)
            over -= r.nbytes
        return victims

    def doc(self):
        return {"models": sorted(self.models),
                "bytes": self.nbytes(),
                "pins": {m: n for m, n in sorted(self.pins.items())},
                "generation": self.generation}


def write_weights_manifest(params_path, params=None):
    """Write the page-in manifest beside an ``.npz`` weights artifact:
    per-var shape/dtype plus the artifact's sha256 — what makes a
    page-in a *manifest-verified* staged load (the member refuses a
    truncated or switched artifact BEFORE any weight touches its
    scope). Returns the manifest path."""
    import hashlib

    import numpy as np
    if params is None:
        params = {k: np.asarray(v)
                  for k, v in np.load(params_path).items()}
    h = hashlib.sha256()
    with open(params_path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    manifest = {
        "sha256": h.hexdigest(),
        "bytes": os.path.getsize(params_path),
        "vars": {name: {"shape": list(np.shape(v)),
                        "dtype": str(np.asarray(v).dtype)}
                 for name, v in sorted(params.items())},
    }
    path = str(params_path) + ".manifest.json"
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def verify_weights_manifest(params_path):
    """Verify an ``.npz`` artifact against its manifest, if one
    exists. Returns the manifest dict (None when unmanifested — the
    legacy pre-paging push shape stays loadable); raises ValueError
    on a digest or size mismatch — the staged load never starts."""
    import hashlib
    path = str(params_path) + ".manifest.json"
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    size = os.path.getsize(params_path)
    if int(manifest.get("bytes", -1)) != size:
        raise ValueError(
            "weights artifact %s is %d bytes, manifest says %s"
            % (params_path, size, manifest.get("bytes")))
    h = hashlib.sha256()
    with open(params_path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    if h.hexdigest() != manifest.get("sha256"):
        raise ValueError(
            "weights artifact %s fails its manifest digest — "
            "truncated or switched push" % (params_path,))
    return manifest
