"""ServingEngine: bucketed, replicated inference front end.

The reference served models through ``paddle/capi`` one request at a
time (``capi/examples/model_inference/``); on TPU the dominant costs are
different — XLA recompiles per input *shape* and a single request
under-fills the MXU — so the serving engine is built around three
JAX/XLA idioms:

* **batch buckets**: every incoming batch is zero-padded up to a fixed
  bucket size, so the Executor's flag-keyed compile cache sees a small
  closed set of shapes and steady-state traffic never recompiles.
* **AOT warmup**: each bucket is compiled once at startup (per replica)
  so the first user request doesn't pay multi-second XLA compile
  latency.
* **device replicas**: model state is ``device_put`` onto N devices;
  requests dispatch round-robin, each replica serializing its own runs
  behind a lock (the jitted computation itself is thread-safe, the
  lock keeps per-replica HBM traffic ordered).

Resilience (``serving/resilience.py``): each replica can be wrapped in
a circuit breaker (``breaker_failures`` arg or the
``serving_breaker_failures`` flag) — N consecutive execution failures,
or a single hang past the per-call ``timeout``, open the breaker and
quarantine the replica out of round-robin; failed requests re-dispatch
to the next healthy replica (``paddle_serving_failover_total``), and a
background half-open probe re-runs a warmed bucket to re-admit the
replica after ``breaker_cooldown_ms``. ``run`` accepts an absolute
``deadline`` (or relative ``deadline_ms``) rejected *before* dispatch,
and ``close()`` makes the engine refuse new work (the graceful-drain
story, with ``MicroBatcher.drain()``). With the flags at their
defaults none of this is constructed and ``run`` costs three ``None``
checks over the PR-2 path.

Fault-injection sites (resilience/faults.py, chaos-testable):
``serving_replica_fail`` / ``serving_replica_slow``, both indexed by
replica number.

Quantized artifacts (``io.save_inference_model(..., quantize="int8")``)
load transparently — dequantization happens in ``load_inference_model``
— so the same engine serves f32 and int8 exports.

Metrics (always on — the front door is not a per-op hot path):
``paddle_serving_requests_total``, ``paddle_serving_batches_total``
{bucket}, ``paddle_serving_batch_occupancy``,
``paddle_serving_batch_seconds``{bucket},
``paddle_serving_bucket_compiles_total``{bucket},
``paddle_serving_bucket_overflow_total``, plus the resilience families
(``paddle_serving_failover_total``,
``paddle_serving_breaker_transitions_total``{state},
``paddle_serving_replica_healthy``{replica},
``paddle_serving_deadline_exceeded_total``). Host spans
(``servingRun``) flow to the Chrome trace when the ``telemetry`` flag
is armed.
"""

import itertools
import threading
import time

import numpy as np

import jax

from .. import config as _config
from .. import io as _io
from ..core.executor import Executor
from ..core.scope import Scope
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing
from ..resilience import faults as _faults
from . import resilience as _sres
from .resilience import (BreakerProbe, ReplicaBreaker, ServingDeadlineError,
                         ServingTimeoutError, ServingUnavailableError)

__all__ = ["ServingEngine"]

_REQUESTS = _metrics.REGISTRY.counter(
    "paddle_serving_requests_total",
    "Examples served through ServingEngine.run")
_BATCHES = _metrics.REGISTRY.counter(
    "paddle_serving_batches_total",
    "Batches executed per bucket size", labelnames=("bucket",))
_OCCUPANCY = _metrics.REGISTRY.gauge(
    "paddle_serving_batch_occupancy",
    "Real examples / bucket size of the most recent batch")
_BATCH_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_serving_batch_seconds",
    "Device execute wall time per batch", labelnames=("bucket",))
_BUCKET_COMPILES = _metrics.REGISTRY.counter(
    "paddle_serving_bucket_compiles_total",
    "First-time (compile) executions per bucket per replica",
    labelnames=("bucket",))
_OVERFLOWS = _metrics.REGISTRY.counter(
    "paddle_serving_bucket_overflow_total",
    "Requests larger than the biggest bucket (served unpadded)")


# distinguishes per-replica health gauges when several breaker-armed
# engines share the process-global metric registry
_ENGINE_SEQ = itertools.count()


class _Replica:
    __slots__ = ("index", "exe", "scope", "device", "lock", "seen",
                 "stuck", "guard")

    def __init__(self, index, exe, scope, device):
        self.index = index
        self.exe = exe
        self.scope = scope
        self.device = device
        self.lock = threading.Lock()
        self.seen = set()  # feed signatures already compiled here
        self.stuck = None  # done-Event of a timed-out worker, if any
        self.guard = threading.Lock()  # serializes stuck bookkeeping


class ServingEngine:
    """Loads an exported model once and serves padded-bucket batches.

    ``model_dir`` may be a ``save_inference_model`` dir or a merged
    single-file model. ``buckets`` defaults to the ``serving_buckets``
    config flag. ``replicas`` > 1 copies the weights onto that many
    devices (round-robin over ``jax.devices()``) and fans requests out.

    ``breaker_failures`` / ``breaker_cooldown_ms`` (default: the
    ``serving_breaker_*`` flags; 0 failures = breakers off) arm the
    per-replica circuit breakers. ``timeout`` (seconds) is the default
    per-call execution timeout enforced around every dispatch — a hang
    past it opens the replica's breaker immediately.
    """

    def __init__(self, model_dir, buckets=None, replicas=1, devices=None,
                 warmup=True, place=None, breaker_failures=None,
                 breaker_cooldown_ms=None, timeout=None):
        if buckets is None:
            buckets = _config.get_flag("serving_buckets")
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError("buckets must be positive ints, got %r"
                             % (buckets,))

        exe0 = Executor(place=place)
        scope0 = Scope()
        (self.program, self.feed_names,
         self.fetch_names) = _io.load_inference_model(
             model_dir, exe0, scope=scope0)
        block = self.program.global_block()
        self._feed_specs = {}
        for name in self.feed_names:
            var = block.var_or_none(name)
            if var is not None:
                self._feed_specs[name] = (tuple(var.shape or ()),
                                          np.dtype(var.dtype))

        if devices is None and replicas > 1:
            devs = jax.devices()
            devices = [devs[i % len(devs)] for i in range(replicas)]
        self.replicas = []
        if not devices:
            self.replicas.append(_Replica(0, exe0, scope0, None))
        else:
            host = {n: np.asarray(v) for n, v in scope0.items()}
            for i, dev in enumerate(devices):
                scope = Scope()
                for n, v in host.items():
                    scope.set_var(n, jax.device_put(v, dev))
                exe = exe0 if i == 0 else Executor(place=place)
                self.replicas.append(_Replica(i, exe, scope, dev))
        self._rr = itertools.count()
        self._closed = False
        self._engine_id = next(_ENGINE_SEQ)

        if breaker_failures is None:
            breaker_failures = _config.get_flag("serving_breaker_failures")
        if breaker_cooldown_ms is None:
            breaker_cooldown_ms = _config.get_flag(
                "serving_breaker_cooldown_ms")
        self.default_timeout = timeout
        if breaker_failures:
            self._breakers = [
                ReplicaBreaker(rep.index, breaker_failures,
                               float(breaker_cooldown_ms) / 1e3,
                               label="e%d:%d" % (self._engine_id,
                                                 rep.index))
                for rep in self.replicas]
        else:
            self._breakers = None
        self._probe = None           # BreakerProbe, started lazily
        self._probe_feed = None      # (feed dict, bucket) from warmup
        self._probe_lock = threading.Lock()

        if warmup:
            self.warmup()

    @property
    def max_bucket(self):
        return self.buckets[-1]

    # -- execution -------------------------------------------------------
    def _bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        return None

    def _execute(self, rep, feed, bucket):
        _faults.fire_point("serving_replica_fail", index=rep.index)
        sig = tuple(sorted((n, a.shape) for n, a in feed.items()))
        if rep.device is not None:
            feed = {n: jax.device_put(a, rep.device)
                    for n, a in feed.items()}
        with rep.lock, _tracing.span("servingRun", bucket=bucket):
            _faults.fire_point("serving_replica_slow", index=rep.index)
            outs = rep.exe.run(self.program, feed=feed,
                               fetch_list=self.fetch_names,
                               scope=rep.scope)
            # Only after a successful run: a failed first execution must
            # not suppress the compile counter for the real compile that
            # happens on the next (successful) attempt.
            if sig not in rep.seen:
                rep.seen.add(sig)
                _BUCKET_COMPILES.labels(bucket=bucket).inc()
        return outs

    def _execute_timed(self, rep, feed, bucket, timeout):
        """Run ``_execute`` bounded by ``timeout`` seconds. One worker
        thread is spawned per timed dispatch — ~e-5 s against ms-scale
        batch executions (measured within noise, PROFILE.md round 9),
        and the simplest structure that survives a wedged run: a hung
        device execution can't be cancelled, so it is left to finish on
        its worker thread while the caller gets ServingTimeoutError — the
        breaker quarantines the replica (whose lock the hung run still
        holds) out of rotation. While that earlier worker is still
        wedged, fail fast instead of stacking another blocked thread
        (and its pinned feed arrays) behind the same lock — probes
        against a wedged replica would otherwise leak one thread per
        cooldown."""
        with rep.guard:
            prior = rep.stuck
            if prior is not None:
                if prior.is_set():
                    rep.stuck = None  # the old run finally finished
                else:
                    raise ServingTimeoutError(
                        "replica %d still wedged in an earlier "
                        "execution" % rep.index)
        result = {}
        done = threading.Event()

        def work():
            try:
                result["outs"] = self._execute(rep, feed, bucket)
            except BaseException as exc:
                result["exc"] = exc
            finally:
                done.set()

        worker = threading.Thread(target=work, daemon=True,
                                  name="serving-exec-%d" % rep.index)
        worker.start()
        if not done.wait(timeout):
            with rep.guard:
                # keep the FIRST still-unset marker: concurrent timed
                # calls must not overwrite it with a later one
                if rep.stuck is None or rep.stuck.is_set():
                    rep.stuck = done
            raise ServingTimeoutError(
                "replica %d exceeded the %.3fs execution timeout"
                % (rep.index, timeout))
        if "exc" in result:
            raise result["exc"]
        return result["outs"]

    def _run_once(self, rep, arrays, bucket, timeout):
        t0 = time.perf_counter()
        if timeout is not None:
            outs = self._execute_timed(rep, arrays, bucket, timeout)
        else:
            outs = self._execute(rep, arrays, bucket)
        _BATCH_SECONDS.labels(bucket=bucket).observe(
            time.perf_counter() - t0)
        return outs

    def _prepare(self, feed):
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self.feed_names, feed))
        arrays = {}
        n = None
        for name in self.feed_names:
            if name not in feed:
                raise KeyError("missing feed %r (expects %s)"
                               % (name, self.feed_names))
            a = np.asarray(feed[name])
            if a.ndim == 0:
                raise ValueError("feed %r must be batch-major" % name)
            if n is None:
                n = a.shape[0]
            elif a.shape[0] != n:
                raise ValueError(
                    "inconsistent batch: %r has %d rows, expected %d"
                    % (name, a.shape[0], n))
            arrays[name] = a
        bucket = self._bucket_for(n)
        if bucket is None:
            bucket = n
            _OVERFLOWS.inc()
        elif bucket > n:
            arrays = {name: np.concatenate(
                [a, np.zeros((bucket - n,) + a.shape[1:], a.dtype)])
                for name, a in arrays.items()}
        return arrays, n, bucket

    def _finish(self, outs, n, bucket):
        _REQUESTS.inc(n)
        _BATCHES.labels(bucket=bucket).inc()
        _OCCUPANCY.set(n / float(bucket))
        return [np.asarray(o)[:n]
                if getattr(o, "ndim", 0) > 0 and o.shape[0] == bucket
                else np.asarray(o) for o in outs]

    def _candidates(self):
        """Replica indices to try, in round-robin order. Healthy
        (breaker-closed) replicas only; when NONE is healthy, replicas
        whose cooldown has elapsed (or that are already half-open) are
        offered as trial dispatches — the traffic itself becomes the
        probe."""
        start = next(self._rr)
        n = len(self.replicas)
        order = [(start + i) % n for i in range(n)]
        if self._breakers is None:
            return order
        closed = [i for i in order if self._breakers[i].state == "closed"]
        now = time.monotonic()
        if closed:
            if self._probe is None:
                # No background prober (no warmed bucket to re-run):
                # live traffic is the only re-admission path, so lead
                # with ONE probe-ready replica as the trial — the
                # healthy replicas behind it absorb a failed trial via
                # failover, and success re-admits it. Without this a
                # half-open replica would be stranded out of rotation
                # as soon as any other replica recovers.
                for i in order:
                    breaker = self._breakers[i]
                    if breaker.state == "half_open" \
                            or breaker.ready_to_probe(now):
                        breaker.to_half_open()
                        return [i] + closed  # i is not closed, no dedup
            return closed
        trial = []
        for i in order:
            breaker = self._breakers[i]
            if breaker.state == "half_open" or breaker.ready_to_probe(now):
                breaker.to_half_open()
                trial.append(i)
        return trial

    def run(self, feed, timeout=None, deadline=None, deadline_ms=None):
        """Serve one batch: pads to the nearest bucket, dispatches to the
        next healthy replica, slices outputs back to the real batch
        size. ``feed``: {name: array} or positional list; arrays are
        batch-major. Thread-safe.

        ``timeout``: seconds to bound THIS execution (defaults to the
        engine's ``timeout``); a hang raises ServingTimeoutError and
        opens the replica's breaker. ``deadline``: absolute
        ``time.monotonic()`` deadline (or ``deadline_ms`` relative to
        now) checked *before* dispatch — an expired request raises
        ServingDeadlineError without ever occupying a device. On an
        execution failure the request fails over to the next healthy
        replica; it only raises when no replica can take it."""
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        if timeout is None:
            timeout = self.default_timeout
        if deadline is None and deadline_ms:  # 0/None = no deadline
            deadline = time.monotonic() + float(deadline_ms) / 1e3
        if deadline is not None and time.monotonic() >= deadline:
            # already doomed: refuse before the padding copies and
            # before touching round-robin/breaker state
            _sres.DEADLINE_EXCEEDED.inc()
            raise ServingDeadlineError("deadline expired before dispatch")
        arrays, n, bucket = self._prepare(feed)

        if self._breakers is None and timeout is None and deadline is None:
            # PR-2 healthy fast path: no resilience bookkeeping at all.
            rep = self.replicas[next(self._rr) % len(self.replicas)]
            outs = self._run_once(rep, arrays, bucket, None)
            return self._finish(outs, n, bucket)

        candidates = self._candidates()
        if not candidates:
            raise ServingUnavailableError(
                "no healthy replica (all %d breakers open)"
                % len(self.replicas))
        last_exc = None
        charged = False  # a breaker already blamed for THIS request
        for pos, idx in enumerate(candidates):
            if deadline is not None and time.monotonic() >= deadline:
                _sres.DEADLINE_EXCEEDED.inc()
                raise ServingDeadlineError(
                    "deadline expired before dispatch")
            rep = self.replicas[idx]
            breaker = self._breakers[idx] if self._breakers else None
            try:
                outs = self._run_once(rep, arrays, bucket, timeout)
            except Exception as exc:
                last_exc = exc
                if breaker is None:
                    raise
                hang = isinstance(exc, ServingTimeoutError)
                # A request that already failed on another replica is
                # almost certainly poison (bad feed content) — charge
                # at most ONE breaker per request so a few bad requests
                # can't open every breaker and black out healthy
                # replicas. Hangs are always the replica's fault, and a
                # half-open trial failure must always record (a breaker
                # left dangling in half_open would never be probed or
                # dispatched to again once another replica recovers).
                if hang or not charged or breaker.state == "half_open":
                    breaker.record_failure(hang=hang)
                    charged = True
                self._ensure_probe()
                if pos + 1 == len(candidates):
                    raise
                _sres.FAILOVER.inc()
                continue
            if breaker is not None:
                breaker.record_success()
            return self._finish(outs, n, bucket)
        raise last_exc  # pragma: no cover (loop always returns/raises)

    # -- resilience ------------------------------------------------------
    def _ensure_probe(self):
        """Start the background half-open prober the first time any
        breaker opens (needs a warmed bucket to re-execute; without
        warmup, re-admission falls back to trial dispatches)."""
        if self._probe is not None or self._probe_feed is None:
            return
        with self._probe_lock:
            if self._probe is None and not self._closed:
                probe = BreakerProbe(self._breakers, self._probe_replica)
                probe.start()
                self._probe = probe

    def _probe_replica(self, index):
        feed, bucket = self._probe_feed
        timeout = self.default_timeout
        if timeout is None:
            timeout = max(30.0, *(b.cooldown for b in self._breakers))
        self._execute_timed(self.replicas[index], feed, bucket, timeout)

    def replica_health(self):
        """Breaker state per replica ('closed' = in rotation); all
        'closed' when breakers are disarmed."""
        if self._breakers is None:
            return ["closed"] * len(self.replicas)
        return [b.state for b in self._breakers]

    def close(self):
        """Refuse new work and stop the probe thread. In-flight runs
        finish; the process is left cleanly restartable (a new engine
        over the same export rebuilds everything)."""
        with self._probe_lock:  # vs a racing _ensure_probe start
            self._closed = True
            probe, self._probe = self._probe, None
        if probe is not None:
            probe.stop()
        if self._breakers is not None:
            for breaker in self._breakers:
                # drop this engine's health gauge children so redeploy
                # cycles don't accumulate stale per-engine labels;
                # retire first so a straggling probe/run can't
                # resurrect the child
                breaker.retired = True
                _sres.REPLICA_HEALTHY.remove(replica=breaker.label)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- startup ---------------------------------------------------------
    def warmup(self, example_feed=None):
        """Compile every bucket on every replica ahead of traffic.
        Feature dims come from the program's feed vars; a model with
        dynamic (non-batch) dims needs ``example_feed`` — one example
        per feed name, WITHOUT the batch dim. Returns the warmed
        buckets. The smallest warmed bucket also becomes the breaker
        probe's health-check execution."""
        warmed = []
        for b in self.buckets:
            feed = {}
            for name in self.feed_names:
                if example_feed is not None and name in example_feed:
                    ex = np.asarray(example_feed[name])
                    feed[name] = np.zeros((b,) + ex.shape, ex.dtype)
                    continue
                spec = self._feed_specs.get(name)
                if spec is None or any(d < 0 for d in spec[0][1:]):
                    feed = None  # dynamic feature dim, can't synthesize
                    break
                feed[name] = np.zeros((b,) + tuple(spec[0][1:]), spec[1])
            if feed is None:
                continue
            for rep in self.replicas:
                self._execute(rep, feed, b)
            if not warmed:
                self._probe_feed = (feed, b)
            warmed.append(b)
        return warmed
