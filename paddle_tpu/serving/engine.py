"""ServingEngine: bucketed, replicated inference front end.

The reference served models through ``paddle/capi`` one request at a
time (``capi/examples/model_inference/``); on TPU the dominant costs are
different — XLA recompiles per input *shape* and a single request
under-fills the MXU — so the serving engine is built around three
JAX/XLA idioms:

* **batch buckets**: every incoming batch is zero-padded up to a fixed
  bucket size, so the Executor's flag-keyed compile cache sees a small
  closed set of shapes and steady-state traffic never recompiles.
* **AOT warmup**: each bucket is compiled once at startup (per replica)
  so the first user request doesn't pay multi-second XLA compile
  latency.
* **device replicas**: model state is ``device_put`` onto N devices;
  requests dispatch round-robin, each replica serializing its own runs
  behind a lock (the jitted computation itself is thread-safe, the
  lock keeps per-replica HBM traffic ordered).

Quantized artifacts (``io.save_inference_model(..., quantize="int8")``)
load transparently — dequantization happens in ``load_inference_model``
— so the same engine serves f32 and int8 exports.

Metrics (always on — the front door is not a per-op hot path):
``paddle_serving_requests_total``, ``paddle_serving_batches_total``
{bucket}, ``paddle_serving_batch_occupancy``,
``paddle_serving_batch_seconds``{bucket},
``paddle_serving_bucket_compiles_total``{bucket},
``paddle_serving_bucket_overflow_total``. Host spans (``servingRun``)
flow to the Chrome trace when the ``telemetry`` flag is armed.
"""

import itertools
import threading
import time

import numpy as np

import jax

from .. import config as _config
from .. import io as _io
from ..core.executor import Executor
from ..core.scope import Scope
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing

__all__ = ["ServingEngine"]

_REQUESTS = _metrics.REGISTRY.counter(
    "paddle_serving_requests_total",
    "Examples served through ServingEngine.run")
_BATCHES = _metrics.REGISTRY.counter(
    "paddle_serving_batches_total",
    "Batches executed per bucket size", labelnames=("bucket",))
_OCCUPANCY = _metrics.REGISTRY.gauge(
    "paddle_serving_batch_occupancy",
    "Real examples / bucket size of the most recent batch")
_BATCH_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_serving_batch_seconds",
    "Device execute wall time per batch", labelnames=("bucket",))
_BUCKET_COMPILES = _metrics.REGISTRY.counter(
    "paddle_serving_bucket_compiles_total",
    "First-time (compile) executions per bucket per replica",
    labelnames=("bucket",))
_OVERFLOWS = _metrics.REGISTRY.counter(
    "paddle_serving_bucket_overflow_total",
    "Requests larger than the biggest bucket (served unpadded)")


class _Replica:
    __slots__ = ("exe", "scope", "device", "lock", "seen")

    def __init__(self, exe, scope, device):
        self.exe = exe
        self.scope = scope
        self.device = device
        self.lock = threading.Lock()
        self.seen = set()  # feed signatures already compiled here


class ServingEngine:
    """Loads an exported model once and serves padded-bucket batches.

    ``model_dir`` may be a ``save_inference_model`` dir or a merged
    single-file model. ``buckets`` defaults to the ``serving_buckets``
    config flag. ``replicas`` > 1 copies the weights onto that many
    devices (round-robin over ``jax.devices()``) and fans requests out.
    """

    def __init__(self, model_dir, buckets=None, replicas=1, devices=None,
                 warmup=True, place=None):
        if buckets is None:
            buckets = _config.get_flag("serving_buckets")
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError("buckets must be positive ints, got %r"
                             % (buckets,))

        exe0 = Executor(place=place)
        scope0 = Scope()
        (self.program, self.feed_names,
         self.fetch_names) = _io.load_inference_model(
             model_dir, exe0, scope=scope0)
        block = self.program.global_block()
        self._feed_specs = {}
        for name in self.feed_names:
            var = block.var_or_none(name)
            if var is not None:
                self._feed_specs[name] = (tuple(var.shape or ()),
                                          np.dtype(var.dtype))

        if devices is None and replicas > 1:
            devs = jax.devices()
            devices = [devs[i % len(devs)] for i in range(replicas)]
        self.replicas = []
        if not devices:
            self.replicas.append(_Replica(exe0, scope0, None))
        else:
            host = {n: np.asarray(v) for n, v in scope0.items()}
            for i, dev in enumerate(devices):
                scope = Scope()
                for n, v in host.items():
                    scope.set_var(n, jax.device_put(v, dev))
                exe = exe0 if i == 0 else Executor(place=place)
                self.replicas.append(_Replica(exe, scope, dev))
        self._rr = itertools.count()
        if warmup:
            self.warmup()

    @property
    def max_bucket(self):
        return self.buckets[-1]

    # -- execution -------------------------------------------------------
    def _bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        return None

    def _execute(self, rep, feed, bucket):
        sig = tuple(sorted((n, a.shape) for n, a in feed.items()))
        if sig not in rep.seen:
            rep.seen.add(sig)
            _BUCKET_COMPILES.labels(bucket=bucket).inc()
        if rep.device is not None:
            feed = {n: jax.device_put(a, rep.device)
                    for n, a in feed.items()}
        with rep.lock, _tracing.span("servingRun", bucket=bucket):
            return rep.exe.run(self.program, feed=feed,
                               fetch_list=self.fetch_names,
                               scope=rep.scope)

    def run(self, feed):
        """Serve one batch: pads to the nearest bucket, dispatches to the
        next replica, slices outputs back to the real batch size.
        ``feed``: {name: array} or positional list; arrays are
        batch-major. Thread-safe."""
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self.feed_names, feed))
        arrays = {}
        n = None
        for name in self.feed_names:
            if name not in feed:
                raise KeyError("missing feed %r (expects %s)"
                               % (name, self.feed_names))
            a = np.asarray(feed[name])
            if a.ndim == 0:
                raise ValueError("feed %r must be batch-major" % name)
            if n is None:
                n = a.shape[0]
            elif a.shape[0] != n:
                raise ValueError(
                    "inconsistent batch: %r has %d rows, expected %d"
                    % (name, a.shape[0], n))
            arrays[name] = a
        bucket = self._bucket_for(n)
        if bucket is None:
            bucket = n
            _OVERFLOWS.inc()
        elif bucket > n:
            arrays = {name: np.concatenate(
                [a, np.zeros((bucket - n,) + a.shape[1:], a.dtype)])
                for name, a in arrays.items()}

        rep = self.replicas[next(self._rr) % len(self.replicas)]
        t0 = time.perf_counter()
        outs = self._execute(rep, arrays, bucket)
        _BATCH_SECONDS.labels(bucket=bucket).observe(
            time.perf_counter() - t0)
        _REQUESTS.inc(n)
        _BATCHES.labels(bucket=bucket).inc()
        _OCCUPANCY.set(n / float(bucket))
        return [np.asarray(o)[:n]
                if getattr(o, "ndim", 0) > 0 and o.shape[0] == bucket
                else np.asarray(o) for o in outs]

    # -- startup ---------------------------------------------------------
    def warmup(self, example_feed=None):
        """Compile every bucket on every replica ahead of traffic.
        Feature dims come from the program's feed vars; a model with
        dynamic (non-batch) dims needs ``example_feed`` — one example
        per feed name, WITHOUT the batch dim. Returns the warmed
        buckets."""
        warmed = []
        for b in self.buckets:
            feed = {}
            for name in self.feed_names:
                if example_feed is not None and name in example_feed:
                    ex = np.asarray(example_feed[name])
                    feed[name] = np.zeros((b,) + ex.shape, ex.dtype)
                    continue
                spec = self._feed_specs.get(name)
                if spec is None or any(d < 0 for d in spec[0][1:]):
                    feed = None  # dynamic feature dim, can't synthesize
                    break
                feed[name] = np.zeros((b,) + tuple(spec[0][1:]), spec[1])
            if feed is None:
                continue
            for rep in self.replicas:
                self._execute(rep, feed, b)
            warmed.append(b)
        return warmed
