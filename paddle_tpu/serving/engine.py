"""ServingEngine: bucketed, replicated inference front end.

The reference served models through ``paddle/capi`` one request at a
time (``capi/examples/model_inference/``); on TPU the dominant costs are
different — XLA recompiles per input *shape* and a single request
under-fills the MXU — so the serving engine is built around three
JAX/XLA idioms:

* **batch buckets**: every incoming batch is zero-padded up to a fixed
  bucket size, so the Executor's flag-keyed compile cache sees a small
  closed set of shapes and steady-state traffic never recompiles.
* **AOT warmup**: each bucket is compiled once at startup (per replica)
  so the first user request doesn't pay multi-second XLA compile
  latency.
* **device replicas**: model state is ``device_put`` onto N devices;
  requests dispatch round-robin, each replica serializing its own runs
  behind a lock (the jitted computation itself is thread-safe, the
  lock keeps per-replica HBM traffic ordered).

Resilience (``serving/resilience.py``): each replica can be wrapped in
a circuit breaker (``breaker_failures`` arg or the
``serving_breaker_failures`` flag) — N consecutive execution failures,
or a single hang past the per-call ``timeout``, open the breaker and
quarantine the replica out of round-robin; failed requests re-dispatch
to the next healthy replica (``paddle_serving_failover_total``), and a
background half-open probe re-runs a warmed bucket to re-admit the
replica after ``breaker_cooldown_ms``. ``run`` accepts an absolute
``deadline`` (or relative ``deadline_ms``) rejected *before* dispatch,
and ``close()`` makes the engine refuse new work (the graceful-drain
story, with ``MicroBatcher.drain()``). With the flags at their
defaults none of this is constructed and ``run`` costs three ``None``
checks over the PR-2 path.

Fault-injection sites (resilience/faults.py, chaos-testable):
``serving_replica_fail`` / ``serving_replica_slow``, both indexed by
replica number.

Quantized artifacts (``io.save_inference_model(..., quantize="int8")``)
load transparently — dequantization happens in ``load_inference_model``
— so the same engine serves f32 and int8 exports.

Metrics (always on — the front door is not a per-op hot path):
``paddle_serving_requests_total``, ``paddle_serving_batches_total``
{bucket}, ``paddle_serving_batch_occupancy``,
``paddle_serving_batch_seconds``{bucket},
``paddle_serving_bucket_compiles_total``{bucket},
``paddle_serving_bucket_overflow_total``, plus the resilience families
(``paddle_serving_failover_total``,
``paddle_serving_breaker_transitions_total``{state},
``paddle_serving_replica_healthy``{replica},
``paddle_serving_deadline_exceeded_total``). Host spans
(``servingRun``) flow to the Chrome trace when the ``telemetry`` flag
is armed.
"""

import itertools
import os
import threading
import time
import weakref

import numpy as np

import jax

from .. import config as _config
from .. import io as _io
from ..core import compile_cache as _cc
from ..core.executor import Executor
from ..core.scope import Scope
from ..observability import metrics as _metrics
from ..observability import request_trace as _rtrace
from ..observability import tracing as _tracing
from ..resilience import faults as _faults
from ..utils import log as _log
from . import deploy as _deploy
from . import resilience as _sres
from .deploy import SwapRejectedError
from .resilience import (BreakerProbe, ReplicaBreaker, ServingDeadlineError,
                         ServingTimeoutError, ServingUnavailableError)

__all__ = ["ServingEngine", "SwapRejectedError"]

_REQUESTS = _metrics.REGISTRY.counter(
    "paddle_serving_requests_total",
    "Examples served through ServingEngine.run")
_BATCHES = _metrics.REGISTRY.counter(
    "paddle_serving_batches_total",
    "Batches executed per bucket size", labelnames=("bucket",))
_OCCUPANCY = _metrics.REGISTRY.gauge(
    "paddle_serving_batch_occupancy",
    "Real examples / bucket size of the most recent batch")
_BATCH_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_serving_batch_seconds",
    "Device execute wall time per batch", labelnames=("bucket",))
_BUCKET_COMPILES = _metrics.REGISTRY.counter(
    "paddle_serving_bucket_compiles_total",
    "First-time (compile) executions per bucket per replica",
    labelnames=("bucket",))
_OVERFLOWS = _metrics.REGISTRY.counter(
    "paddle_serving_bucket_overflow_total",
    "Requests larger than the biggest bucket (served unpadded)")


# distinguishes per-replica health gauges when several breaker-armed
# engines share the process-global metric registry
_ENGINE_SEQ = itertools.count()


def _engine_health(ref):
    """The /healthz component callable for one engine: healthy while
    any replica's breaker is in rotation; None once the engine is
    garbage-collected (the health registry drops it lazily)."""
    def snapshot():
        eng = ref()
        if eng is None:
            return None
        states = eng.replica_health()
        return {"healthy": not eng._closed and
                any(s != "open" for s in states),
                "closed": eng._closed,
                "replicas": states}
    return snapshot


class _Replica:
    __slots__ = ("index", "exe", "scope", "device", "lock", "seen",
                 "stuck", "guard")

    def __init__(self, index, exe, scope, device):
        self.index = index
        self.exe = exe
        self.scope = scope
        self.device = device
        self.lock = threading.Lock()
        self.seen = set()  # feed signatures already compiled here
        self.stuck = None  # done-Event of a timed-out worker, if any
        self.guard = threading.Lock()  # serializes stuck bookkeeping


class ServingEngine:
    """Loads an exported model once and serves padded-bucket batches.

    ``model_dir`` may be a ``save_inference_model`` dir or a merged
    single-file model. ``buckets`` defaults to the ``serving_buckets``
    config flag. ``replicas`` > 1 copies the weights onto that many
    devices (round-robin over ``jax.devices()``) and fans requests out.

    ``breaker_failures`` / ``breaker_cooldown_ms`` (default: the
    ``serving_breaker_*`` flags; 0 failures = breakers off) arm the
    per-replica circuit breakers. ``timeout`` (seconds) is the default
    per-call execution timeout enforced around every dispatch — a hang
    past it opens the replica's breaker immediately.
    """

    def __init__(self, model_dir, buckets=None, replicas=1, devices=None,
                 warmup=True, place=None, breaker_failures=None,
                 breaker_cooldown_ms=None, timeout=None,
                 use_exported=True):
        t_cold = time.perf_counter()
        if buckets is None:
            buckets = _config.get_flag("serving_buckets")
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError("buckets must be positive ints, got %r"
                             % (buckets,))

        exe0 = Executor(place=place)
        scope0 = Scope()
        self.model_dir = model_dir
        self._unpacked_dir = None
        artifact_dir = model_dir
        if os.path.isfile(model_dir):
            # merged single-file artifact: unpack ONCE and keep the
            # dir for the engine's lifetime (removed in close()), so
            # the embedded compiled/ executables are servable too —
            # load_inference_model's internal unpack is discarded
            # after the params land
            from ..utils.merge_model import unpack_merged_model
            artifact_dir = self._unpacked_dir = \
                unpack_merged_model(model_dir)
        self._artifact_dir = artifact_dir
        try:
            # construction-time flag read: int8 artifacts serve their
            # weights AS int8 through the MXU (serving/quant.py).
            # Remembered for swap_weights: a staged push must load
            # through the SAME quant path, or an f32 push can never
            # match an int8-armed engine's dtype signature.
            self._quant_compute = bool(
                _config.get_flag("serving_quant_compute"))
            (self.program, self.feed_names,
             self.fetch_names) = _io.load_inference_model(
                 artifact_dir, exe0, scope=scope0,
                 quant_compute=self._quant_compute)
            # the exact variable set an artifact loads — the
            # shape/dtype signature swap_weights validates a new push
            # against
            self._param_names = tuple(sorted(scope0.var_names()))
            block = self.program.global_block()
            self._feed_specs = {}
            for name in self.feed_names:
                var = block.var_or_none(name)
                if var is not None:
                    self._feed_specs[name] = (tuple(var.shape or ()),
                                              np.dtype(var.dtype))

            if devices is None and replicas > 1:
                devs = jax.devices()
                devices = [devs[i % len(devs)] for i in range(replicas)]
            self.replicas = []
            if not devices:
                self.replicas.append(_Replica(0, exe0, scope0, None))
            else:
                host = {n: np.asarray(v) for n, v in scope0.items()}
                for i, dev in enumerate(devices):
                    scope = Scope()
                    for n, v in host.items():
                        scope.set_var(n, jax.device_put(v, dev))
                    exe = exe0 if i == 0 else Executor(place=place)
                    self.replicas.append(_Replica(i, exe, scope, dev))
            self._rr = itertools.count()
            self._closed = False
            self._engine_id = next(_ENGINE_SEQ)

            if breaker_failures is None:
                breaker_failures = _config.get_flag(
                    "serving_breaker_failures")
            if breaker_cooldown_ms is None:
                breaker_cooldown_ms = _config.get_flag(
                    "serving_breaker_cooldown_ms")
            self.default_timeout = timeout
            if breaker_failures:
                self._breakers = [
                    ReplicaBreaker(rep.index, breaker_failures,
                                   float(breaker_cooldown_ms) / 1e3,
                                   label="e%d:%d" % (self._engine_id,
                                                     rep.index))
                    for rep in self.replicas]
            else:
                self._breakers = None
            self._probe = None          # BreakerProbe, started lazily
            self._probe_feed = None     # (feed dict, bucket) from warmup
            self._probe_lock = threading.Lock()

            # deploy layer (engine-local; None/0 until a swap installs
            # a watch — the default request path costs one None check)
            self._swap_admin = threading.Lock()  # serializes swaps
            self._swap_lock = threading.Lock()   # guards watch state
            self._swap_watch = None
            self._weights_version = 0
            # {replica_index: values} a rollback could not install
            # because the replica was wedged — applied under its lock
            # before its next execution (None = nothing pending)
            self._pending_restore = None
            # True from the instant a watch failure DECIDES to roll
            # back until the restore flip lands: concurrent failing
            # requests see it and hold for the retry instead of
            # surfacing the bad push (the version bump alone leaves a
            # gap between the decision and the flip)
            self._rollback_pending = False
            # AOT-exported executables (io.save_inference_model(...,
            # export_compiled=True)): warmup deserializes instead of
            # compiling; absent/skewed/corrupt entries fall back
            # silently
            self._aot_index = _deploy.load_compiled_index(artifact_dir) \
                if use_exported else None

            # live introspection: /healthz aggregates every live
            # engine's replica-breaker view (weakref — a GC'd engine
            # drops out lazily; close() unregisters eagerly)
            from ..observability import health as _health
            self._health_name = "engine%d" % self._engine_id
            _health.register_health(self._health_name,
                                  _engine_health(weakref.ref(self)))

            if warmup:
                self.warmup()
        except Exception:
            # a failed construction (bad manifest, warmup error) must
            # not leak the unpacked merged-model copy — close() will
            # never run; an autoscaler retrying a bad push would fill
            # the temp filesystem one model copy per attempt
            unpacked, self._unpacked_dir = self._unpacked_dir, None
            if unpacked is not None:
                import shutil
                shutil.rmtree(unpacked, ignore_errors=True)
            # nor a phantom /healthz component: the half-built engine
            # stays referenced by the raised exception's traceback, so
            # the weakref would keep reporting it "healthy" while it
            # serves nothing
            if getattr(self, "_health_name", None):
                from ..observability import health as _health
                _health.unregister_health(self._health_name)
            raise
        _deploy.COLD_START_SECONDS.set(time.perf_counter() - t_cold)

    @property
    def max_bucket(self):
        return self.buckets[-1]

    # -- execution -------------------------------------------------------
    def _bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        return None

    def _apply_pending_restore(self, rep):
        """Install the restore values a rollback left pending for this
        replica (it was wedged when the fleet flipped back). Caller
        holds ``rep.lock``, so no batch can interleave."""
        with self._swap_lock:
            pending = self._pending_restore
            vals = pending.pop(rep.index, None) if pending else None
            if pending is not None and not pending:
                self._pending_restore = None
        if vals:
            for name, val in vals.items():
                rep.scope.set_var(name, val)
            _log.structured("swap_flip_recovered", replica=rep.index)

    def _activated_execute(self, rep, feed, bucket, ctx):
        # the trace context is activated HERE — around the _execute
        # call, not at the run() call site — because the timed path
        # runs this on run_bounded's worker thread, where the caller's
        # thread-local would be invisible: the device-call span must
        # follow the execution wherever it runs
        with _rtrace.activate(ctx):
            return self._execute(rep, feed, bucket)

    def _execute(self, rep, feed, bucket):
        _faults.fire_point("serving_replica_fail", index=rep.index)
        sig = tuple(sorted((n, a.shape) for n, a in feed.items()))
        if rep.device is not None:
            feed = {n: jax.device_put(a, rep.device)
                    for n, a in feed.items()}
        with rep.lock, _tracing.span("servingRun", bucket=bucket):
            if self._pending_restore is not None:
                self._apply_pending_restore(rep)
            _faults.fire_point("serving_replica_slow", index=rep.index)
            outs = rep.exe.run(self.program, feed=feed,
                               fetch_list=self.fetch_names,
                               scope=rep.scope)
            # Only after a successful run: a failed first execution must
            # not suppress the compile counter for the real compile that
            # happens on the next (successful) attempt.
            if sig not in rep.seen:
                rep.seen.add(sig)
                _BUCKET_COMPILES.labels(bucket=bucket).inc()
        return outs

    def _execute_timed(self, rep, feed, bucket, timeout, ctx=None):
        """Run ``_execute`` bounded by ``timeout`` seconds via the
        shared worker-thread pattern (``resilience.run_bounded``): a
        hung device execution is left to finish on its worker thread
        while the caller gets ServingTimeoutError — the breaker
        quarantines the replica (whose lock the hung run still holds)
        out of rotation. While that earlier worker is still wedged,
        fail fast instead of stacking another blocked thread (and its
        pinned feed arrays) behind the same lock — probes against a
        wedged replica would otherwise leak one thread per cooldown."""
        with rep.guard:
            prior = rep.stuck
            if prior is not None:
                if prior.is_set():
                    rep.stuck = None  # the old run finally finished
                else:
                    raise ServingTimeoutError(
                        "replica %d still wedged in an earlier "
                        "execution" % rep.index)
        try:
            return _sres.run_bounded(
                lambda: self._activated_execute(rep, feed, bucket, ctx),
                timeout, name="serving-exec-%d" % rep.index)
        except ServingTimeoutError as err:
            pending = getattr(err, "pending", None)
            if pending is not None:
                with rep.guard:
                    # keep the FIRST still-unset marker: concurrent
                    # timed calls must not overwrite it with a later
                    # one
                    if rep.stuck is None or rep.stuck.is_set():
                        rep.stuck = pending
            raise

    def _run_once(self, rep, arrays, bucket, timeout, ctx=None):
        t0 = time.perf_counter()
        if ctx is not None:
            _rtrace.event(ctx, "dispatch", replica=rep.index,
                          bucket=bucket)
        if timeout is not None:
            outs = self._execute_timed(rep, arrays, bucket, timeout,
                                       ctx=ctx)
        else:
            outs = self._activated_execute(rep, arrays, bucket, ctx)
        _BATCH_SECONDS.labels(bucket=bucket).observe(
            time.perf_counter() - t0)
        return outs

    def _prepare(self, feed):
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self.feed_names, feed))
        arrays = {}
        n = None
        for name in self.feed_names:
            if name not in feed:
                raise KeyError("missing feed %r (expects %s)"
                               % (name, self.feed_names))
            a = np.asarray(feed[name])
            if a.ndim == 0:
                raise ValueError("feed %r must be batch-major" % name)
            if n is None:
                n = a.shape[0]
            elif a.shape[0] != n:
                raise ValueError(
                    "inconsistent batch: %r has %d rows, expected %d"
                    % (name, a.shape[0], n))
            arrays[name] = a
        bucket = self._bucket_for(n)
        if bucket is None:
            bucket = n
            _OVERFLOWS.inc()
        elif bucket > n:
            arrays = {name: np.concatenate(
                [a, np.zeros((bucket - n,) + a.shape[1:], a.dtype)])
                for name, a in arrays.items()}
        return arrays, n, bucket

    def _finish(self, outs, n, bucket):
        _REQUESTS.inc(n)
        _BATCHES.labels(bucket=bucket).inc()
        _OCCUPANCY.set(n / float(bucket))
        return [np.asarray(o)[:n]
                if getattr(o, "ndim", 0) > 0 and o.shape[0] == bucket
                else np.asarray(o) for o in outs]

    def _candidates(self):
        """Replica indices to try, in round-robin order. Healthy
        (breaker-closed) replicas only; when NONE is healthy, replicas
        whose cooldown has elapsed (or that are already half-open) are
        offered as trial dispatches — the traffic itself becomes the
        probe."""
        start = next(self._rr)
        n = len(self.replicas)
        order = [(start + i) % n for i in range(n)]
        if self._breakers is None:
            return order
        closed = [i for i in order if self._breakers[i].state == "closed"]
        now = time.monotonic()
        if closed:
            if self._probe is None:
                # No background prober (no warmed bucket to re-run):
                # live traffic is the only re-admission path, so lead
                # with ONE probe-ready replica as the trial — the
                # healthy replicas behind it absorb a failed trial via
                # failover, and success re-admits it. Without this a
                # half-open replica would be stranded out of rotation
                # as soon as any other replica recovers.
                for i in order:
                    breaker = self._breakers[i]
                    if breaker.state == "half_open" \
                            or breaker.ready_to_probe(now):
                        breaker.to_half_open()
                        return [i] + closed  # i is not closed, no dedup
            return closed
        trial = []
        for i in order:
            breaker = self._breakers[i]
            if breaker.state == "half_open" or breaker.ready_to_probe(now):
                breaker.to_half_open()
                trial.append(i)
        return trial

    def run(self, feed, timeout=None, deadline=None, deadline_ms=None):
        """Serve one batch: pads to the nearest bucket, dispatches to the
        next healthy replica, slices outputs back to the real batch
        size. ``feed``: {name: array} or positional list; arrays are
        batch-major. Thread-safe.

        ``timeout``: seconds to bound THIS execution (defaults to the
        engine's ``timeout``); a hang raises ServingTimeoutError and
        opens the replica's breaker. ``deadline``: absolute
        ``time.monotonic()`` deadline (or ``deadline_ms`` relative to
        now) checked *before* dispatch — an expired request raises
        ServingDeadlineError without ever occupying a device. On an
        execution failure the request fails over to the next healthy
        replica; it only raises when no replica can take it.

        While a post-swap watch window is active (``swap_weights``),
        every execution failure feeds the rollback trigger; the request
        whose failure trips the rollback — and any concurrent request
        whose failure raced the rollback flip — is transparently
        retried once against the restored weights, so no caller ever
        sees the bad push."""
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        if timeout is None:
            timeout = self.default_timeout
        if deadline is None and deadline_ms:  # 0/None = no deadline
            deadline = time.monotonic() + float(deadline_ms) / 1e3
        if deadline is not None and time.monotonic() >= deadline:
            # already doomed: refuse before the padding copies and
            # before touching round-robin/breaker state
            _sres.DEADLINE_EXCEEDED.inc()
            raise ServingDeadlineError("deadline expired before dispatch")
        # a batcher flush arrives with its lead request's context
        # already active (or the NO_TRACE sentinel, when the front
        # door above us sampled nothing — minting here would fill the
        # bounded store with orphan traces the operator chose not to
        # record); only a DIRECT engine call mints its own, and only
        # AFTER feed validation: a malformed-feed storm must not
        # churn real traces out of the bounded store with root-only
        # orphans. One attribute read when request_tracing is off.
        ctx = _rtrace.current()
        if ctx is not None and ctx.trace_id is None:
            ctx = None
            mint_own = False
        else:
            mint_own = ctx is None
        arrays, n, bucket = self._prepare(feed)
        if mint_own:
            ctx = _rtrace.mint("serving.run", bucket=bucket, n=int(n))
        # terminal edges (resolve/resolveError/deadlineExpired) are
        # recorded only on traces minted HERE: for an inherited
        # context the batcher owns the Future and records the one
        # ending — the engine contributes lifecycle edges only
        # (dispatch, failover, deviceCall).
        v0 = self._weights_version  # detect a mid-request weight flip

        if self._breakers is None and timeout is None and \
                deadline is None and self._swap_watch is None and \
                not self._rollback_pending:
            # PR-2 healthy fast path: no resilience bookkeeping at
            # all. A pending rollback routes through the slow path so
            # a request dispatched onto the about-to-be-restored
            # weights gets the transparent retry, not the bad push.
            rep = self.replicas[next(self._rr) % len(self.replicas)]
            try:
                outs = self._run_once(rep, arrays, bucket, None,
                                      ctx=ctx)
            except Exception as exc:
                if self._swap_watch is None and \
                        not self._rollback_pending and \
                        not self._swap_admin.locked() and \
                        self._weights_version == v0:
                    if mint_own and ctx is not None:
                        _rtrace.event(ctx, "resolveError",
                                      error=repr(exc)[:200])
                    raise  # a plain failure, no swap anywhere near it
                # a swap/rollback raced this dispatch (the guard saw
                # pre-swap state, the execution saw the new weights):
                # fall through to the slow path, which owns the
                # watch/retry bookkeeping
            else:
                if mint_own and ctx is not None:
                    _rtrace.event(ctx, "resolve", bucket=bucket,
                                  n=int(n))
                return self._finish(outs, n, bucket)

        last_exc = None
        charged = False  # a breaker already blamed for THIS request
        for attempt in (0, 1):
            candidates = self._candidates()
            if not candidates:
                if mint_own and ctx is not None:
                    _rtrace.event(ctx, "resolveError",
                                  error="no healthy replica")
                raise ServingUnavailableError(
                    "no healthy replica (all %d breakers open)"
                    % len(self.replicas))
            retry = False
            for pos, idx in enumerate(candidates):
                if deadline is not None and time.monotonic() >= deadline:
                    _sres.DEADLINE_EXCEEDED.inc()
                    if mint_own and ctx is not None:
                        _rtrace.event(ctx, "deadlineExpired",
                                      where="before dispatch")
                    raise ServingDeadlineError(
                        "deadline expired before dispatch")
                rep = self.replicas[idx]
                breaker = self._breakers[idx] if self._breakers else None
                try:
                    outs = self._run_once(rep, arrays, bucket, timeout,
                                          ctx=ctx)
                except Exception as exc:
                    last_exc = exc
                    final = breaker is None or \
                        pos + 1 == len(candidates)
                    # post-swap watch: ONE outcome per REQUEST (the
                    # breaker's charge-at-most-once discipline) — a
                    # poison request failing over across every replica
                    # must count as a single failure, not burn the
                    # whole consecutive budget and roll back a healthy
                    # push. Noted only at the final candidate; True =
                    # the prior weights were just restored, so this
                    # request deserves one transparent retry instead
                    # of surfacing the bad push to its caller.
                    rolled = self._swap_note(False) \
                        if final and self._swap_watch is not None \
                        else False
                    if breaker is not None:
                        hang = isinstance(exc, ServingTimeoutError)
                        # A request that already failed on another
                        # replica is almost certainly poison (bad feed
                        # content) — charge at most ONE breaker per
                        # request so a few bad requests can't open
                        # every breaker and black out healthy replicas.
                        # Hangs are always the replica's fault, and a
                        # half-open trial failure must always record (a
                        # breaker left dangling in half_open would
                        # never be probed or dispatched to again once
                        # another replica recovers).
                        if hang or not charged or \
                                breaker.state == "half_open":
                            breaker.record_failure(hang=hang)
                            charged = True
                        self._ensure_probe()
                    if final:
                        if not rolled and attempt == 0 and \
                                (self._weights_version != v0 or
                                 self._rollback_pending or
                                 self._swap_admin.locked()):
                            # A CONCURRENT request's rollback (or a
                            # swap) replaced the weights this run
                            # failed against — or its flip is still
                            # in flight (decided, or admin lock
                            # held; wait it out). Either way this
                            # request deserves the same transparent
                            # retry as the one that tripped the
                            # rollback: no caller may see the bad
                            # push.
                            wait_until = time.monotonic() + \
                                self.FLIP_LOCK_TIMEOUT
                            if deadline is not None:
                                # the wait must respect the caller's
                                # deadline — the PR-5 contract bounds
                                # run() by it, swap or no swap
                                wait_until = min(wait_until, deadline)
                            while (self._rollback_pending or
                                   self._swap_admin.locked()) and \
                                    time.monotonic() < wait_until:
                                time.sleep(0.001)  # let the flip land
                            rolled = self._weights_version != v0
                        if rolled and attempt == 0:
                            retry = True
                            break
                        if mint_own and ctx is not None:
                            _rtrace.event(ctx, "resolveError",
                                          error=repr(exc)[:200])
                        raise
                    _sres.FAILOVER.inc()
                    if ctx is not None:
                        _rtrace.event(ctx, "failover",
                                      from_replica=idx,
                                      hang=isinstance(
                                          exc, ServingTimeoutError),
                                      error=repr(exc)[:200])
                    continue
                if breaker is not None:
                    breaker.record_success()
                if self._swap_watch is not None:
                    self._swap_note(True)
                if mint_own and ctx is not None:
                    _rtrace.event(ctx, "resolve", bucket=bucket,
                                  n=int(n))
                return self._finish(outs, n, bucket)
            if not retry:
                break
        if mint_own and ctx is not None:
            _rtrace.event(ctx, "resolveError",
                          error=repr(last_exc)[:200])
        raise last_exc

    # -- resilience ------------------------------------------------------
    def _ensure_probe(self):
        """Start the background half-open prober the first time any
        breaker opens (needs a warmed bucket to re-execute; without
        warmup, re-admission falls back to trial dispatches)."""
        if self._probe is not None or self._probe_feed is None:
            return
        with self._probe_lock:
            if self._probe is None and not self._closed:
                probe = BreakerProbe(self._breakers, self._probe_replica)
                probe.start()
                self._probe = probe

    def _probe_replica(self, index):
        feed, bucket = self._probe_feed
        timeout = self.default_timeout
        if timeout is None:
            timeout = max(30.0, *(b.cooldown for b in self._breakers))
        self._execute_timed(self.replicas[index], feed, bucket, timeout)

    def replica_health(self):
        """Breaker state per replica ('closed' = in rotation); all
        'closed' when breakers are disarmed."""
        if self._breakers is None:
            return ["closed"] * len(self.replicas)
        return [b.state for b in self._breakers]

    def close(self):
        """Refuse new work and stop the probe thread. In-flight runs
        finish; the process is left cleanly restartable (a new engine
        over the same export rebuilds everything)."""
        from ..observability import health as _health
        _health.unregister_health(getattr(self, "_health_name", ""))
        with self._probe_lock:  # vs a racing _ensure_probe start
            self._closed = True
            probe, self._probe = self._probe, None
        if probe is not None:
            probe.stop()
        unpacked, self._unpacked_dir = self._unpacked_dir, None
        if unpacked is not None:
            import shutil
            shutil.rmtree(unpacked, ignore_errors=True)
        if self._breakers is not None:
            for breaker in self._breakers:
                # retire first so a straggling probe/run can't
                # resurrect a gauge child the sweep below removes
                breaker.retired = True
            # drop every family's children labelled on this engine's
            # "e<N>:*" namespace in one registry sweep, so redeploy
            # cycles don't accumulate stale per-engine labels (the
            # scheduler tier's close() discipline)
            _metrics.REGISTRY.remove_labeled(
                "replica", prefix="e%d:" % self._engine_id)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- deploy: hot weight swap ----------------------------------------
    @property
    def weights_version(self):
        """Monotonic counter of weight flips (initial load = 0; every
        swap or rollback bumps it)."""
        return self._weights_version

    def _canary_feed(self):
        """A warmed bucket's feed for the canary run (warmup recorded
        one; otherwise synthesize the smallest bucket)."""
        if self._probe_feed is not None:
            return self._probe_feed
        for b, feed in _deploy._bucket_feeds(
                self.program.global_block(), self.feed_names,
                self.buckets[:1]):
            return feed, b
        return None

    # Bound on waiting for a replica's execution lock during a swap
    # flip or canary: a replica wedged in a hung device execution
    # holds its lock indefinitely (PR-5 leaves the stuck worker with
    # it by design), and an unbounded acquire here would deadlock
    # every future swap AND auto-rollback behind _swap_admin.
    FLIP_LOCK_TIMEOUT = 30.0

    def _run_canary(self, new_host):
        """Execute one warmed bucket against the NEW weights in a
        throwaway scope — same program, same compiled entry, zero
        contact with live traffic's weights. Non-finite outputs or any
        execution error reject the push. Runs on the first
        breaker-healthy replica (a quarantined/wedged replica must not
        stall the canary)."""
        probe = self._canary_feed()
        if probe is None:
            _log.structured("swap_canary_skipped",
                            reason="no synthesizable bucket feed")
            return
        feed, bucket = probe
        cscope = Scope()
        for name, val in new_host.items():
            cscope.set_var(name, val)
        rep = self.replicas[0]
        if self._breakers is not None:
            for i, breaker in enumerate(self._breakers):
                if breaker.state == "closed":
                    rep = self.replicas[i]
                    break
        if not rep.lock.acquire(timeout=self.FLIP_LOCK_TIMEOUT):
            raise RuntimeError(
                "replica %d execution lock not acquired within %.0fs "
                "(wedged execution?) — canary could not run"
                % (rep.index, self.FLIP_LOCK_TIMEOUT))
        try:
            with _tracing.span("swapCanary", bucket=bucket):
                outs = rep.exe.run(self.program, feed=feed,
                                   fetch_list=self.fetch_names,
                                   scope=cscope)
        finally:
            rep.lock.release()
        for out in outs:
            arr = np.asarray(out)
            if np.issubdtype(arr.dtype, np.floating) and \
                    not np.all(np.isfinite(arr)):
                raise ValueError("canary produced non-finite outputs")

    def _flip(self, per_replica_values, skip_wedged=False,
              prior_out=None):
        """Install ``per_replica_values[rep.index]`` into each replica's
        scope under its execution lock — every batch therefore runs
        against exactly one weight version (the lock is what serializes
        batches, PR-2), and replicas not currently being flipped keep
        serving. Values are staged onto each replica's device BEFORE
        any lock is taken, so the lock window is pointer flips, not
        transfers. Replicas missing from ``per_replica_values`` are
        skipped (partial-restore dicts).

        A replica whose lock can't be had within FLIP_LOCK_TIMEOUT is
        wedged in a hung execution: with ``skip_wedged`` (the rollback
        path — the flip must make progress) it is skipped with a log;
        otherwise the replicas already flipped are restored and
        SwapRejectedError raised — a half-flipped fleet never serves.

        Returns the prior per-replica values (the rollback state) and
        observes the worst single-replica lock hold as the swap
        blackout."""
        worst = 0.0
        # prior_out lets swap_weights hand the SAME dict to a
        # pre-installed watch, so a rollback tripped mid-flip (it
        # serializes behind _swap_admin, which the swap still holds)
        # always sees the fully-populated restore state
        prior = prior_out if prior_out is not None else {}
        for rep in self.replicas:
            vals = per_replica_values.get(rep.index)
            if vals is None:
                continue
            if not rep.lock.acquire(timeout=self.FLIP_LOCK_TIMEOUT):
                if skip_wedged:
                    # leave the values PENDING: they are applied under
                    # the replica's lock before its next execution
                    # (_apply_pending_restore), so a recovered replica
                    # can never serve a batch on the weights this flip
                    # meant to replace
                    with self._swap_lock:
                        if self._pending_restore is None:
                            self._pending_restore = {}
                        self._pending_restore[rep.index] = vals
                    _log.structured("swap_flip_skipped_wedged",
                                    replica=rep.index)
                    continue
                self._flip(prior, skip_wedged=True)  # restore flipped
                raise SwapRejectedError(
                    "replica %d execution lock not acquired within "
                    "%.0fs (wedged execution?) — swap aborted, prior "
                    "weights restored" % (rep.index,
                                          self.FLIP_LOCK_TIMEOUT))
            try:
                t0 = time.perf_counter()
                prior[rep.index] = {n: rep.scope.find_var(n)
                                    for n in vals}
                for name, val in vals.items():
                    rep.scope.set_var(name, val)
                worst = max(worst, time.perf_counter() - t0)
                if self._pending_restore is not None:
                    # this flip just installed NEWER values: a stale
                    # pending restore must not clobber them later
                    with self._swap_lock:
                        if self._pending_restore is not None:
                            self._pending_restore.pop(rep.index, None)
                            if not self._pending_restore:
                                self._pending_restore = None
            finally:
                rep.lock.release()
        _deploy.SWAP_BLACKOUT_SECONDS.observe(worst)
        return prior

    def _stage(self, new_host):
        """Per-replica device copies of the new weights, transfers
        completed up front (kept out of the flip's lock window)."""
        staged = {}
        for rep in self.replicas:
            if rep.device is None:
                staged[rep.index] = dict(new_host)
            else:
                vals = {n: jax.device_put(v, rep.device)
                        for n, v in new_host.items()}
                for val in vals.values():
                    val.block_until_ready()
                staged[rep.index] = vals
        return staged

    def swap_weights(self, model_dir, canary=True, watch_requests=50,
                     watch_failures=3):
        """Hot-swap the engine onto the weights in ``model_dir``
        without dropping traffic. Returns the new weights version.

        The push lands in three gates, each of which rejects with
        :class:`SwapRejectedError` while the prior weights keep
        serving untouched:

        1. **validate** — artifact sha256 manifest verification, then a
           full load into a staging scope and a parameter-set +
           shape/dtype signature match against the live weights (the
           program is NOT swapped: same architecture, new values — so
           every compiled bucket survives the swap).
        2. **canary** — one warmed-bucket execution against the new
           weights in a throwaway scope (replica 0, under its batch
           lock); errors or non-finite outputs reject the push.
        3. **flip** — per replica, under its execution lock: stage the
           new values onto the device first, then swap scope pointers
           between drained batches. No batch ever sees mixed versions;
           the blackout is the lock-held pointer flip
           (``paddle_deploy_swap_blackout_seconds``).

        After the flip a watch window arms: ``watch_failures``
        CONSECUTIVE execution failures within the next
        ``watch_requests`` requests auto-roll back to the prior
        weights (counted in ``paddle_deploy_swap_rolled_back_total``),
        and the request that trips the rollback retries transparently
        against the restored weights. ``watch_requests=0`` disarms the
        watch (the swap commits immediately)."""
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        with self._swap_admin:
            _deploy.SWAP_TOTAL.inc()
            try:
                _faults.fire_point("swap_bad_artifact")
                stage_scope = Scope()
                # load_inference_model digest-verifies manifested
                # artifacts before trusting the params (one hash per
                # member — no separate verify pass) and raises the
                # reason into this block
                program2, feeds2, fetches2 = _io.load_inference_model(
                    model_dir, Executor(), scope=stage_scope,
                    quant_compute=self._quant_compute)
                if self._quant_compute and \
                        getattr(self.program, "_quant_compute",
                                None) and \
                        not getattr(program2, "_quant_compute",
                                    None):
                    # the engine serves int8-armed weights but the
                    # push is a plain f32 artifact (no quant.json —
                    # install_quant_compute was a no-op): quantize
                    # the staged scope in place so the push gains the
                    # int8 vars + @quant.scale sidecars the live
                    # signature check expects. Without this, ANY f32
                    # push to an int8-armed engine trips the dtype
                    # gate — and so does the rollback that follows.
                    from . import quant as _quant
                    _quant.arm_quant_compute([program2], stage_scope)
                if list(feeds2) != list(self.feed_names) or \
                        list(fetches2) != list(self.fetch_names):
                    raise ValueError(
                        "feed/fetch signature mismatch: push has "
                        "%s -> %s, engine serves %s -> %s"
                        % (feeds2, fetches2, self.feed_names,
                           self.fetch_names))
                new_host = {n: np.asarray(v)
                            for n, v in stage_scope.items()}
                if tuple(sorted(new_host)) != self._param_names:
                    raise ValueError(
                        "parameter set mismatch: push has %d vars, "
                        "engine serves %d" % (len(new_host),
                                              len(self._param_names)))
                live = self.replicas[0].scope
                for name, val in new_host.items():
                    cur = live.find_var(name)
                    if tuple(val.shape) != tuple(cur.shape) or \
                            np.dtype(val.dtype) != np.dtype(cur.dtype):
                        raise ValueError(
                            "signature mismatch on %r: push %s/%s vs "
                            "live %s/%s" % (name, val.shape, val.dtype,
                                            tuple(cur.shape), cur.dtype))
            except Exception as exc:
                _deploy.SWAP_ROLLED_BACK.inc()
                _log.structured("swap_rejected", stage="validate",
                                model_dir=str(model_dir),
                                error=repr(exc))
                raise SwapRejectedError(
                    "weight push rejected during validation: %s"
                    % (exc,)) from exc
            if canary:
                try:
                    _faults.fire_point("swap_canary_fail")
                    self._run_canary(new_host)
                except Exception as exc:
                    _deploy.SWAP_ROLLED_BACK.inc()
                    _log.structured("swap_rejected", stage="canary",
                                    model_dir=str(model_dir),
                                    error=repr(exc))
                    raise SwapRejectedError(
                        "canary run failed — push rejected: %s"
                        % (exc,)) from exc
            staged = self._stage(new_host)
            # Install the watch BEFORE the flip: the instant any
            # replica serves the new weights, a failure there must
            # find the watch armed — installing it after the flip
            # leaves a window where a bad push's failures take the
            # fast path or surface to clients. The watch shares the
            # ``prior`` dict the flip populates; a rollback tripped
            # mid-flip blocks on _swap_admin (held here) until the
            # flip is complete, so it always restores the full fleet.
            prior = {}
            with self._swap_lock:
                self._swap_watch = None if not watch_requests else {
                    "prior": prior,
                    "remaining": int(watch_requests),
                    "consecutive": 0,
                    "threshold": max(1, int(watch_failures)),
                    "version": self._weights_version + 1,
                }
            try:
                self._flip(staged, prior_out=prior)
            except SwapRejectedError:
                # a wedged replica aborted the flip mid-way; the
                # already-flipped replicas were restored — the push
                # did not land
                with self._swap_lock:
                    self._swap_watch = None
                _deploy.SWAP_ROLLED_BACK.inc()
                _log.structured("swap_rejected", stage="flip",
                                model_dir=str(model_dir))
                raise
            with self._swap_lock:
                self._weights_version += 1
                version = self._weights_version
            _log.structured("swap_committed", model_dir=str(model_dir),
                            version=version,
                            watch_requests=int(watch_requests))
            return version

    def _swap_note(self, ok):
        """Feed one request outcome to the post-swap watch. Returns
        True when THIS failure tripped the auto-rollback (the caller
        then retries once against the restored weights)."""
        rollback_prior = rollback_version = None
        with self._swap_lock:
            watch = self._swap_watch
            if watch is None:
                return False
            if ok:
                watch["consecutive"] = 0
            else:
                watch["consecutive"] += 1
            watch["remaining"] -= 1
            if not ok and watch["consecutive"] >= watch["threshold"]:
                rollback_prior = watch["prior"]
                rollback_version = watch["version"]
                self._swap_watch = None
                self._rollback_pending = True
            elif watch["remaining"] <= 0:
                self._swap_watch = None
                _log.structured("swap_watch_committed",
                                version=watch["version"])
        if rollback_prior is None:
            return False
        # Serialize the restore flip with swap_weights: a concurrent
        # swap's flip must never interleave with this one (the two
        # would leave replicas on MIXED versions — per-replica lock
        # order differs), and if a newer swap already landed while we
        # raced for the admin lock, its weights supersede the bad push
        # — there is nothing left to restore.
        try:
            with self._swap_admin:
                with self._swap_lock:
                    if self._weights_version != rollback_version:
                        _log.structured("swap_rollback_superseded",
                                        watched_version=rollback_version,
                                        current=self._weights_version)
                        return False
                # the restore must make progress past a wedged replica
                # — its values stay PENDING and are installed under
                # its lock before its next execution
                # (_apply_pending_restore), so recovery can't
                # resurrect the rejected weights
                self._flip(rollback_prior, skip_wedged=True)
                with self._swap_lock:
                    self._weights_version += 1
        finally:
            with self._swap_lock:
                self._rollback_pending = False
        _deploy.SWAP_ROLLED_BACK.inc()
        _log.structured("swap_rolled_back",
                        restored_version=self._weights_version)
        return True

    # -- startup ---------------------------------------------------------
    def _prime_bucket(self, bucket, feed):
        """Prime one bucket from the artifact's AOT-exported executable
        instead of compiling it: verify the blob's sha256 against the
        ``compiled/index.json`` entry, deserialize once, and install it
        as each eligible replica's cache-entry executable — gated by the
        executor cache digest, so version/flag/topology skew can never
        install an executable that computes something else. Returns
        {replica_index: executor cache entry} for the replicas primed
        (warmup executes each and only THEN counts the AOT load — or a
        fallback, if the call degraded); every prime miss here is a
        counted fallback to the normal compile-warmup path."""
        index = self._aot_index
        entry = (index or {}).get("buckets", {}).get(str(bucket))
        if entry is None:
            return {}
        dev_id = (index or {}).get("device_id")
        compiled = None
        primed = {}
        for rep in self.replicas:
            try:
                if not entry.get("digest"):
                    # no digest = no gate: never install an executable
                    # the executor can't prove is THIS computation
                    raise ValueError(
                        "index entry for bucket %d carries no "
                        "executor digest" % bucket)
                if rep.device is not None and rep.device.id != dev_id:
                    raise ValueError(
                        "replica device %d != exported device %r"
                        % (rep.device.id, dev_id))
                if compiled is None:
                    blob = _deploy.read_compiled_blob(
                        self._artifact_dir, entry)
                    compiled = _cc.deserialize_compiled(blob)
                cache_entry = rep.exe.prime_aot(
                    self.program, feed, self.fetch_names, rep.scope,
                    compiled, expect_digest=entry["digest"])
            except Exception as e:
                _deploy.AOT_FALLBACKS.inc()
                _log.structured("aot_prime_fallback", bucket=bucket,
                                replica=rep.index, error=repr(e))
                continue
            # suppress the per-bucket compile counter for the primed
            # execution (warmup re-counts honestly if the call
            # degrades to a real compile)
            rep.seen.add(tuple(sorted((n, a.shape)
                               for n, a in feed.items())))
            primed[rep.index] = cache_entry
        return primed

    def warmup(self, example_feed=None):
        """Make every bucket on every replica ready ahead of traffic:
        deserialize the artifact's AOT-exported executable when one
        matches (cold start skips the XLA compile entirely), compile as
        before otherwise. Feature dims come from the program's feed
        vars; a model with dynamic (non-batch) dims needs
        ``example_feed`` — one example per feed name, WITHOUT the batch
        dim. Returns the warmed buckets. The smallest warmed bucket
        also becomes the breaker probe's health-check execution."""
        specs = {}
        for name in self.feed_names:
            if example_feed is not None and name in example_feed:
                ex = np.asarray(example_feed[name])
                specs[name] = (ex.shape, ex.dtype)
                continue
            spec = self._feed_specs.get(name)
            if spec is None:
                return []  # unknown feed var: nothing synthesizable
            specs[name] = (tuple(spec[0][1:]), spec[1])
        warmed = []
        for b in self.buckets:
            # the ONE feed synthesis shared with export (deploy.py) —
            # same shapes + dtypes ⇒ the AOT digests recorded at
            # export time match this engine's cache entries
            feed = _deploy.synth_bucket_feed(specs, b)
            if feed is None:
                continue  # dynamic feature dim, can't synthesize
            primed = self._prime_bucket(b, feed) \
                if self._aot_index else {}
            for rep in self.replicas:
                # primed replicas execute too: one batch through the
                # deserialized executable validates it NOW — a
                # call-incompatible blob degrades to the jit path at
                # warmup, not as a compile stall on the first live
                # request — and only a SURVIVING executable counts as
                # an AOT load
                self._execute(rep, feed, b)
                centry = primed.get(rep.index)
                if centry is None:
                    continue
                if centry.aot is not None and not centry.aot_failed:
                    _deploy.AOT_LOADS.inc()
                else:
                    # the call degraded mid-execution: that WAS a jit
                    # compile — the cold start must not report clean
                    _deploy.AOT_FALLBACKS.inc()
                    _BUCKET_COMPILES.labels(bucket=b).inc()
                    _log.structured("aot_prime_call_fallback",
                                    bucket=b, replica=rep.index)
            if not warmed:
                self._probe_feed = (feed, b)
            warmed.append(b)
        return warmed
