"""Micro-batching front door: ``submit(feed) -> Future``.

Concurrent single-example requests (the serving traffic shape — many
users, one example each) coalesce into bucket-sized batches before
hitting the device: the dispatcher thread takes the first queued
request, then keeps gathering until the batch fills or a max-latency
deadline expires, stacks the examples batch-major, and runs them
through the :class:`~paddle_tpu.serving.engine.ServingEngine` as ONE
padded-bucket execution. Each caller's Future resolves to its own row
of the outputs, so the batching is invisible to clients.

Admission control, outermost first:

* **backpressure** — a bounded queue: ``submit`` blocks while it is
  full (or raises :class:`ServingOverloadError` when a ``timeout`` is
  given) instead of letting an unbounded backlog grow.
* **adaptive shedding** — the dispatcher tracks an EWMA of observed
  queue waits; a submit carrying a deadline whose budget the projected
  wait would already blow is shed IMMEDIATELY with
  :class:`ServingOverloadError`, so overload is refused at the door
  while the caller can still retry elsewhere, not discovered by a
  full-queue timeout at the worst moment.
* **deadlines** — ``submit(feed, deadline_ms=...)`` (default: the
  ``serving_deadline_ms`` flag; 0 = none) attaches an absolute
  deadline; items that expire while queued are dropped at dispatch
  with :class:`ServingDeadlineError` *before* the batch hits a device,
  so doomed work never occupies one.

Each example is validated against the engine's feed specs at
``submit`` time, and the flush groups co-batched items by shape, so
one malformed request can never poison its neighbours' batch.

``drain()`` is the redeploy story: stop admission, serve everything
already accepted, stop the dispatcher — every accepted Future
resolves, and the process is left cleanly restartable. ``close()`` is
the fast exit (bounded wait, leftovers failed). Fault site
``serving_overload`` (resilience/faults.py) forces sheds for chaos
tests.

Metrics: ``paddle_serving_request_seconds`` (submit -> result latency
histogram), ``paddle_serving_queue_depth`` (gauge, reset to 0 on
close/drain), ``paddle_serving_shed_total`` and
``paddle_serving_deadline_exceeded_total`` (serving/resilience.py).
"""

import itertools
import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from .. import config as _config
from ..observability import flight as _flight
from ..observability import metrics as _metrics
from ..observability import request_trace as _rtrace
from ..observability import tracing as _tracing
from ..resilience import faults as _faults
from . import resilience as _sres
from .resilience import ServingDeadlineError

__all__ = ["MicroBatcher", "ServingOverloadError", "TenantQuotaError"]

_REQUEST_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_serving_request_seconds",
    "Per-request latency, submit() to Future resolution")
_QUEUE_DEPTH = _metrics.REGISTRY.gauge(
    "paddle_serving_queue_depth",
    "Requests waiting in the micro-batcher queue")


class ServingOverloadError(RuntimeError):
    """Admission refused: the bounded queue stayed full past the submit
    timeout, or the projected queue wait exceeds the deadline budget."""


class TenantQuotaError(ServingOverloadError):
    """Admission refused because THIS tenant is over its in-flight
    quota — an overload scoped to one tenant, so callers (and the
    chaos probes) can tell "the fleet is full" from "you are bursting".
    Carries the tenant id as ``.tenant``."""

    def __init__(self, tenant, message):
        super().__init__(message)
        self.tenant = tenant


class _WorkItem:
    __slots__ = ("feed", "future", "t_submit", "deadline", "ctx")

    def __init__(self, feed, deadline=None):
        self.feed = feed
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline  # absolute time.monotonic(), or None
        # request-scoped TraceContext (None = tracing off / unsampled)
        self.ctx = None


_STOP = object()

# EWMA smoothing for observed queue waits (~ the last ten batches
# dominate, so the projection tracks load swings without flapping).
_WAIT_ALPHA = 0.2


def _resolve(future, result=None, exception=None):
    """Set a Future's outcome without letting a client-side cancel()
    (racing the cancelled() check) raise InvalidStateError and kill the
    dispatcher thread.

    Every exceptional resolution across the serving stack funnels
    through here, which makes it the one flight-recorder hook for
    "client-visible error": armed, a failure storm auto-dumps one
    debounced post-mortem bundle; disarmed, it is one attribute
    check."""
    if exception is not None:
        _flight.RECORDER.client_error(exception)
    try:
        if not future.cancelled():
            if exception is not None:
                future.set_exception(exception)
            else:
                future.set_result(result)
    except Exception:
        pass  # already cancelled/resolved: the client walked away


class MicroBatcher:
    """Coalesces single-example submissions into engine batches.

    ``submit`` takes one example per feed name WITHOUT the batch dim
    (it is stacked on axis 0 here); the Future resolves to the list of
    per-example fetch outputs. ``max_batch`` defaults to the engine's
    largest bucket; ``max_delay_ms`` bounds the extra latency a lone
    request pays waiting for company.
    """

    def __init__(self, engine, max_batch=None, max_delay_ms=5.0,
                 max_queue=256, autostart=True):
        self.engine = engine
        self.max_batch = int(max_batch or engine.max_bucket)
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_delay = float(max_delay_ms) / 1e3
        self._q = queue.Queue(maxsize=max_queue)
        self._thread = None
        self._closed = False
        self._wait_ewma = 0.0  # seconds an item recently waited queued
        self._submit_seq = itertools.count()  # atomic under the GIL
        if autostart:
            self.start()

    def start(self):
        if self._closed:
            raise RuntimeError("batcher is closed")
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="micro-batcher",
                                            daemon=True)
            self._thread.start()
        return self

    def _validate(self, name, a):
        """Reject a malformed example at the door (its caller alone),
        instead of letting np.stack/XLA fail the whole coalesced batch
        it would have ridden in."""
        spec = self.engine._feed_specs.get(name)
        if spec is None:
            return
        dims = tuple(spec[0][1:])  # per-example dims, batch dim dropped
        if len(a.shape) != len(dims) or any(
                d >= 0 and s != d for d, s in zip(dims, a.shape)):
            raise ValueError(
                "feed %r: example shape %s does not match the model's "
                "per-example spec %s (submit() takes ONE example, "
                "without the batch dim)" % (name, a.shape, dims))
        if a.dtype.kind in "OSUV":  # object/str/void: poison for XLA
            raise ValueError(
                "feed %r: example dtype %s is not numeric (model "
                "expects %s)" % (name, a.dtype, spec[1]))

    def submit(self, feed, timeout=None, deadline_ms=None,
               tenant=None):
        """Enqueue one example; returns a Future of its outputs.

        ``deadline_ms``: serve-by budget from now (default: the
        ``serving_deadline_ms`` flag; 0/None = no deadline). An already
        hopeless submit is refused synchronously —
        :class:`ServingOverloadError` when the projected queue wait
        exceeds the budget, :class:`ServingDeadlineError` when the
        budget is gone — and an item whose deadline passes while queued
        resolves its Future with :class:`ServingDeadlineError` without
        reaching a device. ``timeout``: seconds to wait on a full
        queue; raises :class:`ServingOverloadError` instead of blocking
        forever. ``tenant``: attribution only — a shed of a
        tenant-tagged submit also charges
        ``paddle_serving_tenant_shed_total{tenant=...}`` (quota
        enforcement itself lives at the fleet router)."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        seq = next(self._submit_seq)
        try:
            _faults.fire_point("serving_overload", index=seq,
                               default_exc=ServingOverloadError)
        except ServingOverloadError:
            _sres.SHED.inc()
            if tenant is not None:
                _sres.TENANT_SHED.labels(tenant=str(tenant)).inc()
            raise
        if deadline_ms is None:
            deadline_ms = _config.get_flag("serving_deadline_ms")
        deadline = None
        if deadline_ms:  # 0/None = no deadline, per the contract
            budget = float(deadline_ms) / 1e3
            if budget < 0:
                _sres.DEADLINE_EXCEEDED.inc()
                raise ServingDeadlineError(
                    "deadline budget %.1f ms already spent"
                    % float(deadline_ms))
            projected = self._wait_ewma * (
                1.0 + self._q.qsize() / float(self.max_batch))
            if projected > budget:
                # Decay the estimate on every shed: only dispatched
                # items update the EWMA, so without this a congestion
                # spike would latch it high on an idle queue and shed
                # deadline traffic forever. Geometric decay re-admits
                # a probe request within a few sheds, and its REAL
                # observed wait re-anchors the estimate.
                self._wait_ewma *= (1.0 - _WAIT_ALPHA)
                _sres.SHED.inc()
                if tenant is not None:
                    _sres.TENANT_SHED.labels(
                        tenant=str(tenant)).inc()
                raise ServingOverloadError(
                    "shed: projected queue wait %.1f ms exceeds the "
                    "%.1f ms deadline budget"
                    % (projected * 1e3, budget * 1e3))
            deadline = time.monotonic() + budget
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self.engine.feed_names, feed))
        arrays = {}
        for name in self.engine.feed_names:
            a = np.asarray(feed[name])
            self._validate(name, a)
            arrays[name] = a
        item = _WorkItem(arrays, deadline=deadline)
        # trace minted at the front door, carried on the queue item;
        # one attribute read when request_tracing is off
        item.ctx = _rtrace.mint("serving.submit", seq=seq)
        try:
            self._q.put(item, block=True, timeout=timeout)
        except queue.Full:
            # never entered the system: a rejection storm must not
            # churn real in-flight traces out of the bounded store
            _rtrace.discard(item.ctx)
            raise ServingOverloadError(
                "serving queue full (%d pending)" % self._q.qsize()) \
                from None
        if self._closed and self._thread is None:
            # Raced a close()/drain() past its leftover sweep: nothing
            # may ever pop this item, so fail OUR future (idempotent —
            # the shutdown sweep may have raced us to it, and _resolve
            # makes a later pop by drain a no-op) and refuse the
            # submit. Only ours: a concurrent drain() still owns and
            # serves every other accepted item.
            _rtrace.discard(item.ctx)
            _resolve(item.future,
                     exception=RuntimeError("batcher closed"))
            raise RuntimeError("batcher is closed")
        _QUEUE_DEPTH.set(self._q.qsize())
        return item.future

    # -- dispatcher ------------------------------------------------------
    def _loop(self):
        while True:
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if first is _STOP:
                return
            batch = [first]
            deadline = time.perf_counter() + self.max_delay
            stop = False
            while len(batch) < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop = True
                    break
                batch.append(nxt)
            _QUEUE_DEPTH.set(self._q.qsize())
            self._flush(batch)
            if stop:
                return

    def _flush(self, batch):
        """Dispatch a gathered batch: drop expired items, then run each
        same-shape group as one engine execution (mixed shapes — only
        possible for feeds with dynamic per-example dims — batch
        separately instead of failing each other)."""
        now = time.monotonic()
        live = []
        for it in batch:
            if it.deadline is not None and now >= it.deadline:
                _sres.DEADLINE_EXCEEDED.inc()
                if it.ctx is not None:
                    _rtrace.event(it.ctx, "deadlineExpired",
                                  where="in queue")
                _resolve(it.future, exception=ServingDeadlineError(
                    "deadline expired after %.1f ms in queue"
                    % ((time.perf_counter() - it.t_submit) * 1e3)))
            else:
                wait = time.perf_counter() - it.t_submit
                self._wait_ewma += _WAIT_ALPHA * (wait - self._wait_ewma)
                _rtrace.QUEUE_WAIT_MS.observe(wait * 1e3)
                if it.ctx is not None:
                    _rtrace.event(it.ctx, "queueWait", dur_ms=wait * 1e3)
                live.append(it)
        if not live:
            return
        names = self.engine.feed_names
        groups = {}
        for it in live:
            # dtype is part of the key: a stray float64/int64 example
            # batches alone instead of upcasting (and poisoning) the
            # whole stacked group
            key = tuple((it.feed[n].shape, it.feed[n].dtype)
                        for n in names)
            groups.setdefault(key, []).append(it)
        for group in groups.values():
            self._flush_group(group)

    def _flush_group(self, batch):
        # the shape-group flush is a lifecycle edge on EVERY sampled
        # member's trace; the engine dispatch (replica choice,
        # failover hops, device call) is activated under the FIRST
        # sampled member's context — co-batched requests share one
        # physical execution, so one trace carries its detail
        lead_ctx = None
        for it in batch:
            if it.ctx is not None:
                if lead_ctx is None:
                    lead_ctx = it.ctx
                _rtrace.event(it.ctx, "shapeGroupFlush",
                              size=len(batch),
                              lead=lead_ctx.trace_id)
        try:
            # nothing sampled -> activate the NO_TRACE sentinel, not
            # None: the engine below must see "sampling already
            # decided against this batch" and not mint its own orphan
            # 'serving.run' trace for it
            with _tracing.span("servingBatch", size=len(batch)), \
                    _rtrace.activate(lead_ctx if lead_ctx is not None
                                     else _rtrace.NO_TRACE):
                feed = {name: np.stack([it.feed[name] for it in batch])
                        for name in self.engine.feed_names}
                outs = self.engine.run(feed)
        except Exception as exc:  # engine failure, every replica down...
            for it in batch:
                if it.ctx is not None:
                    _rtrace.event(it.ctx, "resolveError",
                                  error=repr(exc)[:200])
                _resolve(it.future, exception=exc)
            return
        now = time.perf_counter()
        for i, it in enumerate(batch):
            res = [o[i] if getattr(o, "ndim", 0) > 0 and
                   o.shape[0] == len(batch) else o for o in outs]
            _resolve(it.future, result=res)
            e2e = now - it.t_submit
            _REQUEST_SECONDS.observe(e2e)
            _rtrace.E2E_MS.observe(e2e * 1e3)
            if it.ctx is not None:
                _rtrace.event(it.ctx, "resolve", dur_ms=e2e * 1e3)

    # -- lifecycle -------------------------------------------------------
    def _stop_dispatcher(self, timeout):
        """Common close/drain step: mark closed, wake the dispatcher
        with a stop marker, join it. Returns the items left in the
        queue (racing submits that landed behind the marker). A
        dispatcher wedged mid-batch is disowned, but the queue is
        still emptied — each item is popped exactly once, so the
        caller fails/serves what it got and the wedged thread serves
        only what it already held."""
        self._closed = True
        if self._thread is not None:
            try:
                # never block on a full queue behind a wedged
                # dispatcher — with the marker unplaceable, the sweep
                # below empties the queue and the dispatcher's get loop
                # exits on empty+closed anyway
                self._q.put_nowait(_STOP)
            except queue.Full:
                pass
            self._thread.join(timeout)
            self._thread = None
        leftovers = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                leftovers.append(item)
        return leftovers

    def drain(self, timeout=None):
        """Graceful drain (the redeploy story): stop admission, serve
        every request already accepted — including submits that raced
        the stop marker — and stop the dispatcher. Every accepted
        Future resolves; afterwards the process holds no queued work
        and a fresh batcher/engine can take over."""
        leftovers = self._stop_dispatcher(timeout)
        for i in range(0, len(leftovers), self.max_batch):
            self._flush(leftovers[i:i + self.max_batch])
        _QUEUE_DEPTH.set(0)

    def close(self, timeout=5.0):
        """Drain-and-stop with a bounded wait: queued requests before
        the stop marker still complete; anything after it is failed
        rather than left hanging; subsequent submits raise."""
        for item in self._stop_dispatcher(timeout):
            _resolve(item.future,
                     exception=RuntimeError("batcher closed"))
        _QUEUE_DEPTH.set(0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
