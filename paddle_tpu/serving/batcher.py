"""Micro-batching front door: ``submit(feed) -> Future``.

Concurrent single-example requests (the serving traffic shape — many
users, one example each) coalesce into bucket-sized batches before
hitting the device: the dispatcher thread takes the first queued
request, then keeps gathering until the batch fills or a max-latency
deadline expires, stacks the examples batch-major, and runs them
through the :class:`~paddle_tpu.serving.engine.ServingEngine` as ONE
padded-bucket execution. Each caller's Future resolves to its own row
of the outputs, so the batching is invisible to clients.

Backpressure is a bounded queue: ``submit`` blocks while the queue is
full (or raises :class:`ServingOverloadError` when a ``timeout`` is
given) instead of letting an unbounded backlog grow.

Metrics: ``paddle_serving_request_seconds`` (submit -> result latency
histogram) and ``paddle_serving_queue_depth`` (gauge). Mean batch
occupancy is derivable from the engine's ``requests_total`` /
``batches_total`` counters.
"""

import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..observability import metrics as _metrics
from ..observability import tracing as _tracing

__all__ = ["MicroBatcher", "ServingOverloadError"]

_REQUEST_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_serving_request_seconds",
    "Per-request latency, submit() to Future resolution")
_QUEUE_DEPTH = _metrics.REGISTRY.gauge(
    "paddle_serving_queue_depth",
    "Requests waiting in the micro-batcher queue")


class ServingOverloadError(RuntimeError):
    """The bounded request queue stayed full past the submit timeout."""


class _WorkItem:
    __slots__ = ("feed", "future", "t_submit")

    def __init__(self, feed):
        self.feed = feed
        self.future = Future()
        self.t_submit = time.perf_counter()


_STOP = object()


def _resolve(future, result=None, exception=None):
    """Set a Future's outcome without letting a client-side cancel()
    (racing the cancelled() check) raise InvalidStateError and kill the
    dispatcher thread."""
    try:
        if not future.cancelled():
            if exception is not None:
                future.set_exception(exception)
            else:
                future.set_result(result)
    except Exception:
        pass  # already cancelled/resolved: the client walked away


class MicroBatcher:
    """Coalesces single-example submissions into engine batches.

    ``submit`` takes one example per feed name WITHOUT the batch dim
    (it is stacked on axis 0 here); the Future resolves to the list of
    per-example fetch outputs. ``max_batch`` defaults to the engine's
    largest bucket; ``max_delay_ms`` bounds the extra latency a lone
    request pays waiting for company.
    """

    def __init__(self, engine, max_batch=None, max_delay_ms=5.0,
                 max_queue=256, autostart=True):
        self.engine = engine
        self.max_batch = int(max_batch or engine.max_bucket)
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_delay = float(max_delay_ms) / 1e3
        self._q = queue.Queue(maxsize=max_queue)
        self._thread = None
        self._closed = False
        if autostart:
            self.start()

    def start(self):
        if self._closed:
            raise RuntimeError("batcher is closed")
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="micro-batcher",
                                            daemon=True)
            self._thread.start()
        return self

    def submit(self, feed, timeout=None):
        """Enqueue one example; returns a Future of its outputs. Blocks
        while the queue is full; with ``timeout`` (seconds) raises
        :class:`ServingOverloadError` instead."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self.engine.feed_names, feed))
        item = _WorkItem({n: np.asarray(feed[n])
                          for n in self.engine.feed_names})
        try:
            self._q.put(item, block=True, timeout=timeout)
        except queue.Full:
            raise ServingOverloadError(
                "serving queue full (%d pending)" % self._q.qsize()) \
                from None
        _QUEUE_DEPTH.set(self._q.qsize())
        return item.future

    # -- dispatcher ------------------------------------------------------
    def _loop(self):
        while True:
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if first is _STOP:
                return
            batch = [first]
            deadline = time.perf_counter() + self.max_delay
            stop = False
            while len(batch) < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop = True
                    break
                batch.append(nxt)
            _QUEUE_DEPTH.set(self._q.qsize())
            self._flush(batch)
            if stop:
                return

    def _flush(self, batch):
        try:
            with _tracing.span("servingBatch", size=len(batch)):
                feed = {name: np.stack([it.feed[name] for it in batch])
                        for name in self.engine.feed_names}
                outs = self.engine.run(feed)
        except Exception as exc:  # mismatched shapes, engine failure, ...
            for it in batch:
                _resolve(it.future, exception=exc)
            return
        now = time.perf_counter()
        for i, it in enumerate(batch):
            res = [o[i] if getattr(o, "ndim", 0) > 0 and
                   o.shape[0] == len(batch) else o for o in outs]
            _resolve(it.future, result=res)
            _REQUEST_SECONDS.observe(now - it.t_submit)

    # -- lifecycle -------------------------------------------------------
    def close(self, timeout=5.0):
        """Drain-and-stop: queued requests before the stop marker still
        complete; subsequent submits raise."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            self._q.put(_STOP)
            self._thread.join(timeout)
            self._thread = None
        # A submit() racing close() can land behind the stop marker;
        # fail those futures rather than leave result() hanging forever.
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                _resolve(item.future,
                         exception=RuntimeError("batcher closed"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
