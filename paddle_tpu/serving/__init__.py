"""Inference serving subsystem (the reference's ``paddle/capi``
examples tier, rebuilt TPU-native — see ROADMAP north star).

Four cooperating pieces:

* :mod:`engine`     — :class:`ServingEngine`: loads an exported/merged
  model once, pads requests to fixed batch buckets (the Executor's
  compile cache then sees a closed shape set), AOT-warms every bucket,
  and dispatches round-robin across device replicas — skipping
  replicas whose circuit breaker is open, failing requests over to the
  next healthy replica.
* :mod:`batcher`    — :class:`MicroBatcher`: thread-safe
  ``submit(feed) -> Future`` micro-batching with a max-latency
  deadline, bounded-queue backpressure, per-request serve-by
  deadlines, EWMA-based adaptive load shedding, and a graceful
  ``drain()``.
* :mod:`resilience` — the failure model: :class:`ReplicaBreaker`
  (closed/open/half-open with background probe re-admission),
  :class:`ServingDeadlineError` / :class:`ServingTimeoutError` /
  :class:`ServingUnavailableError`, and the always-on recovery
  counters (``paddle_serving_failover_total``,
  ``paddle_serving_breaker_transitions_total``, ...).
* :mod:`quant`      — post-training int8 weight quantization
  (per-output-channel symmetric scales) wired into
  ``io.save_inference_model(..., quantize="int8")`` and transparently
  dequantized at load.
* :mod:`generation` — the stateful (LLM) tier:
  :class:`GenerationSession` (on-device KV-cache decode batch,
  prefill/step/retire over cache slots) and
  :class:`GenerationScheduler` (continuous batching:
  ``submit(prompt) -> Future`` with deadlines/backpressure/shedding,
  mid-flight slot-level admit/retire, per-session breakers, drain,
  and between-step weight swap).
* :mod:`paged_cache` — the paged-KV memory tier behind
  ``generation_paged_kv``: :class:`BlockPool` (fixed-size block
  allocator with refcounts over the per-layer K/V pools) and
  :class:`PrefixIndex` (content-hashed prompt caching: shared prefix
  blocks, copy-on-write divergence, LRU eviction under pressure).
* :mod:`fleet` — the multi-process tier: :class:`FleetRouter`
  (line-protocol membership with heartbeats and generation fencing,
  least-loaded routing over per-member breakers, cross-process
  token-replay failover, rolling deploys with canary watch and
  fleet-wide rollback) and :class:`EngineWorker` (the process wrapper
  a member runs, streaming tokens over ``wire.py``'s JSON-line
  transport).

Everything is instrumented through :mod:`paddle_tpu.observability`;
``tools/serving_probe.py`` exercises the stack headless and
``tools/serving_chaos_probe.py`` drives it through injected replica
failures and overload (fault sites ``serving_replica_fail`` /
``serving_replica_slow`` / ``serving_overload``).
"""

from . import deploy  # noqa: F401
from . import quant  # noqa: F401
from . import resilience  # noqa: F401
from .resilience import (ServingDeadlineError,  # noqa: F401
                         ServingTimeoutError, ServingUnavailableError,
                         ReplicaBreaker)
from .deploy import SwapRejectedError  # noqa: F401
from .engine import ServingEngine  # noqa: F401
from .batcher import MicroBatcher, ServingOverloadError  # noqa: F401
from .generation import (GenerationScheduler,  # noqa: F401
                         GenerationSession, GenerationSpec)
from .paged_cache import (BlockPool, PoolExhausted,  # noqa: F401
                          PrefixIndex)
from .fleet import EngineWorker, FleetRouter  # noqa: F401
from .wire import WireError  # noqa: F401

__all__ = ["ServingEngine", "MicroBatcher", "ServingOverloadError",
           "ServingDeadlineError", "ServingTimeoutError",
           "ServingUnavailableError", "SwapRejectedError",
           "ReplicaBreaker", "GenerationSession", "GenerationScheduler",
           "GenerationSpec", "BlockPool", "PrefixIndex",
           "PoolExhausted", "FleetRouter", "EngineWorker", "WireError",
           "deploy", "fleet", "generation", "paged_cache",
           "quant", "resilience", "wire"]
