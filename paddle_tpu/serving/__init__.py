"""Inference serving subsystem (the reference's ``paddle/capi``
examples tier, rebuilt TPU-native — see ROADMAP north star).

Three cooperating pieces:

* :mod:`engine`  — :class:`ServingEngine`: loads an exported/merged
  model once, pads requests to fixed batch buckets (the Executor's
  compile cache then sees a closed shape set), AOT-warms every bucket,
  and dispatches round-robin across device replicas.
* :mod:`batcher` — :class:`MicroBatcher`: thread-safe
  ``submit(feed) -> Future`` micro-batching with a max-latency
  deadline and bounded-queue backpressure.
* :mod:`quant`   — post-training int8 weight quantization
  (per-output-channel symmetric scales) wired into
  ``io.save_inference_model(..., quantize="int8")`` and transparently
  dequantized at load.

Everything is instrumented through :mod:`paddle_tpu.observability`;
``tools/serving_probe.py`` exercises the stack headless and prints the
Prometheus exposition.
"""

from . import quant  # noqa: F401
from .engine import ServingEngine  # noqa: F401
from .batcher import MicroBatcher, ServingOverloadError  # noqa: F401

__all__ = ["ServingEngine", "MicroBatcher", "ServingOverloadError",
           "quant"]
