"""Loader for the native (C++) components — builds them on first use.

The reference shipped its native layer prebuilt by CMake; here a make
invocation compiles the small dependency-free C++ sources in native/ into
shared libraries (ctypes, no pybind11 in this image) and the task_master
daemon binary.
"""

import ctypes
import os
import subprocess
import threading

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_ROOT, "native")
_BUILD_DIR = os.path.join(_NATIVE_DIR, "build")
_lock = threading.Lock()
_libs = {}


def build_native():
    with _lock:
        subprocess.run(["make", "-s", "-C", _NATIVE_DIR], check=True)
    return _BUILD_DIR


def _source_for(name):
    """The .cc a build artifact comes from (lib<stem>.so / bare binary)."""
    stem = name
    if stem.startswith("lib") and stem.endswith(".so"):
        stem = stem[3:-3]
    return os.path.join(_NATIVE_DIR, stem + ".cc")


def _ensure(name):
    path = os.path.join(_BUILD_DIR, name)
    src = _source_for(name)
    stale = os.path.exists(path) and os.path.exists(src) and \
        os.path.getmtime(src) > os.path.getmtime(path)
    if not os.path.exists(path) or stale:
        # stale: the artifact predates its source (e.g. a task_master
        # binary from before a protocol change) — make rebuilds only
        # what changed
        build_native()
    return path


def load_lib(stem):
    """Load lib<stem>.so, building if needed."""
    with _lock:
        if stem in _libs:
            return _libs[stem]
    path = _ensure("lib%s.so" % stem)
    lib = ctypes.CDLL(path)
    with _lock:
        _libs[stem] = lib
    return lib


def task_master_binary():
    return _ensure("task_master")


def recordio_lib():
    lib = load_lib("recordio")
    lib.ptrc_writer_open.restype = ctypes.c_void_p
    lib.ptrc_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
    lib.ptrc_writer_write.argtypes = [ctypes.c_void_p,
                                      ctypes.c_char_p, ctypes.c_uint32]
    lib.ptrc_writer_close.argtypes = [ctypes.c_void_p]
    lib.ptrc_reader_open.restype = ctypes.c_void_p
    lib.ptrc_reader_open.argtypes = [ctypes.c_char_p]
    lib.ptrc_reader_num_chunks.argtypes = [ctypes.c_void_p]
    lib.ptrc_reader_load_chunk.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptrc_reader_next.argtypes = [ctypes.c_void_p,
                                     ctypes.c_char_p, ctypes.c_uint32]
    lib.ptrc_reader_peek_len.argtypes = [ctypes.c_void_p]
    lib.ptrc_reader_close.argtypes = [ctypes.c_void_p]
    return lib


def shuffle_pool_lib():
    lib = load_lib("shuffle_pool")
    lib.ptpool_create.restype = ctypes.c_void_p
    lib.ptpool_create.argtypes = [ctypes.c_uint32, ctypes.c_uint32,
                                  ctypes.c_uint32]
    lib.ptpool_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32]
    lib.ptpool_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint32]
    lib.ptpool_close.argtypes = [ctypes.c_void_p]
    lib.ptpool_size.argtypes = [ctypes.c_void_p]
    lib.ptpool_destroy.argtypes = [ctypes.c_void_p]
    return lib


def arena_lib():
    lib = load_lib("buddy_allocator")
    lib.ptarena_create.restype = ctypes.c_void_p
    lib.ptarena_create.argtypes = [ctypes.c_size_t]
    lib.ptarena_alloc.restype = ctypes.c_void_p
    lib.ptarena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.ptarena_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.ptarena_in_use.restype = ctypes.c_size_t
    lib.ptarena_in_use.argtypes = [ctypes.c_void_p]
    lib.ptarena_peak.restype = ctypes.c_size_t
    lib.ptarena_peak.argtypes = [ctypes.c_void_p]
    lib.ptarena_destroy.argtypes = [ctypes.c_void_p]
    return lib


def capi_lib():
    """C inference API (native/capi.cc; reference capi/gradient_machine.h).
    From Python/ctypes it joins the running interpreter; a standalone C
    program links it with libpython and calls ptc_init(repo_path)."""
    lib = load_lib("capi")
    lib.ptc_init.restype = ctypes.c_int
    lib.ptc_init.argtypes = [ctypes.c_char_p]
    lib.ptc_model_load.restype = ctypes.c_void_p
    lib.ptc_model_load.argtypes = [ctypes.c_char_p]
    lib.ptc_model_forward.restype = ctypes.c_int
    lib.ptc_model_num_outputs.restype = ctypes.c_int
    lib.ptc_model_num_outputs.argtypes = [ctypes.c_void_p]
    lib.ptc_model_output_name.restype = ctypes.c_char_p
    lib.ptc_model_output_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptc_model_output_data.restype = ctypes.POINTER(ctypes.c_float)
    lib.ptc_model_output_data.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.POINTER(ctypes.c_int64)]
    lib.ptc_model_output_ndim.restype = ctypes.c_int
    lib.ptc_model_output_ndim.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptc_model_output_dim.restype = ctypes.c_int64
    lib.ptc_model_output_dim.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                         ctypes.c_int]
    lib.ptc_model_release.argtypes = [ctypes.c_void_p]
    return lib


class PtcTensor(ctypes.Structure):
    """Mirror of capi.cc's ptc_tensor."""
    _fields_ = [("name", ctypes.c_char_p),
                ("data", ctypes.c_void_p),
                ("shape", ctypes.POINTER(ctypes.c_int64)),
                ("ndim", ctypes.c_int),
                ("dtype", ctypes.c_int)]
