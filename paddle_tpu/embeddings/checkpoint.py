"""Checkpoint layout metadata + reshard for distributed embedding tables.

A distributed table's scope value is stored in mod-interleaved
(shard-major) layout for whatever shard count the saving strategy used
(sharded.py). Because the padded vocab is shard-count-independent
(PAD_MULTIPLE), resharding across an elastic resize (PR 6:
``resize_strategy`` re-keys the mesh) is a pure row PERMUTATION — the
array shape, the program, and the executor compile entries all survive.

Protocol:

* save side — pass :func:`layout_meta` as ``extra_meta`` to
  ``io.save_checkpoint``: the digest-verified checkpoint's
  ``latest.json`` then carries each table's (and each registered
  optimizer slot's) shard count alongside the resume metadata.
* restore side — after ``io.load_checkpoint`` put the raw (old-layout)
  arrays in the scope, call :func:`reshard_scope` with the saved
  layout and the NEW strategy: every table whose shard count changed is
  re-permuted old->logical->new, optimizer slots included, row-exactly.
"""

import numpy as np

from .sharded import active_shards, to_logical, to_shard_major

__all__ = ["layout_meta", "reshard_scope", "reshard_array"]

META_KEY = "embedding_layout"


def layout_meta(program, strategy=None):
    """``extra_meta`` dict for ``io.save_checkpoint``: the shard layout
    every registered distributed table (and optimizer slot) is stored
    in under ``strategy``."""
    tables = getattr(program, "_dist_embeddings", None) or {}
    out = {}
    for name, info in tables.items():
        n, _, _ = active_shards(strategy, info["padded"])
        out[name] = {"num_shards": int(n), "vocab": int(info["vocab"]),
                     "padded": int(info["padded"]),
                     "dim": int(info["dim"]),
                     "slot_of": info.get("slot_of")}
    return {META_KEY: out}


def reshard_array(arr, old_shards, new_shards):
    """Re-permute one shard-major array across a shard-count change."""
    old_n, new_n = int(old_shards), int(new_shards)
    if old_n == new_n:
        return np.asarray(arr)
    return to_shard_major(to_logical(arr, old_n), new_n)


def reshard_scope(scope, layout, strategy=None):
    """Re-key every restored table in ``scope`` from its saved shard
    count (``layout`` = the ``embedding_layout`` entry of
    ``io.load_checkpoint_meta``, or a full meta dict) to the count
    ``strategy`` implies. Row-shaped optimizer slots ride along; [1]
    accumulators (Adam beta powers) were never registered and pass
    through untouched. Returns the number of re-permuted arrays."""
    if layout and META_KEY in layout:
        layout = layout[META_KEY]
    moved = 0
    for name, info in (layout or {}).items():
        if not scope.has_var(name):
            continue
        old_n = int(info.get("num_shards", 1))
        new_n, _, _ = active_shards(strategy, int(info["padded"]))
        if old_n == new_n:
            continue
        arr = np.asarray(scope.find_var(name))
        if arr.ndim < 1 or arr.shape[0] != int(info["padded"]):
            continue  # defensive: registry drift / foreign var
        scope.set_var(name, reshard_array(arr, old_n, new_n))
        moved += 1
    return moved
