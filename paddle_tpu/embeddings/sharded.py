"""Row-sharded embedding tables — the pserver seam rebuilt for ICI.

The reference system's entire distributed runtime (the C++ pserver and
the Go pserver/master, PAPER.md §2) exists to serve one workload: sparse
embedding lookups against tables too big for any single worker, hashed
across shards by ``row_id % num_shards``
(``SparseParameterDistribution.cpp``). Here that seam is rebuilt as ICI
collectives inside the jitted step instead of parameter-server RPC:

* **Storage** — a distributed table of logical shape ``[vocab, dim]`` is
  materialized as one global ``[padded_vocab, dim]`` array in
  *mod-interleaved (shard-major) layout*: storage row ``s*rps + k``
  holds logical row ``k*n + s`` (``n`` shards, ``rps = padded_vocab/n``
  rows per shard). Under ``NamedSharding P(data_axis, None)`` shard
  ``s``'s contiguous block is then exactly the rows with
  ``id % n == s`` — the pserver hash rule expressed as a layout, so the
  mesh's block placement IS the mod placement. ``padded_vocab`` rounds
  the vocab up to a multiple of :data:`PAD_MULTIPLE` so the same static
  program shape serves any power-of-two shard count (elastic resizes
  re-permute, never reshape — see checkpoint.py).
* **Lookup** — a two-hop ``all_to_all`` inside ``shard_map``
  (jax_compat shim): each device hashes its batch's ids to owning
  shards, exchanges id buckets (hop 1, index wire width), gathers rows
  from its local shard, and exchanges the rows back (hop 2). Bucket
  capacity is the device's own id count, so the exchange is static-
  shaped and skew-proof (a device can never send one shard more ids
  than it has).
* **Gradient** — the backward op reverses the route: output-row
  gradients travel TO the owning shard, are merged per shard
  (``merge_duplicate_rows``), and surface as a SelectedRows-style
  (Rows, Values) pair in global shard-major coordinates — the
  optimizers' existing sparse scatter path applies them. A step never
  materializes a dense gradient the size of the table.

With no mesh (or ``embedding_shard_rows`` off, or a shard count that
doesn't divide the padded vocab) everything degrades to a single-shard
identity layout and a dense gather — numerically identical, zero
collectives. With ``embedding_a2a`` off but sharding on, the gather
goes through the mod layout as a global-view ``take`` and GSPMD picks
the collectives (the compiler-chosen baseline the probe compares
against).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..observability import metrics as _metrics

__all__ = ["PAD_MULTIPLE", "padded_vocab", "to_shard_major", "to_logical",
           "register_table", "dist_tables", "active_shards"]

# Vocab padding granularity: every power-of-two shard count up to 64
# divides it, so one static [padded_vocab, dim] program shape survives
# any elastic resize on a power-of-two mesh (resharding permutes rows,
# it never changes shapes — the executor compile cache keeps its
# entries and checkpoints stay shape-compatible).
PAD_MULTIPLE = 64

# -- always-registered telemetry (recording armed per trace by the
# ``telemetry`` flag; family creation is one-time and free) ------------
_LOOKUP_ROWS = _metrics.REGISTRY.counter(
    "paddle_embedding_lookup_rows_total",
    "Embedding rows looked up through distributed tables (ids per "
    "step, duplicates included)")
_A2A_BYTES = _metrics.REGISTRY.counter(
    "paddle_embedding_a2a_bytes_total",
    "Bytes exchanged over the embedding all_to_all, by payload: "
    "direction=ids (index hops) / direction=rows (row payload hops), "
    "forward and backward both counted",
    labelnames=("direction",))
_UNIQUE_RATIO = _metrics.REGISTRY.gauge(
    "paddle_embedding_unique_ratio",
    "Unique/total ids of the last distributed-lookup batch (duplicate "
    "merge leverage: low ratio = merge_duplicate_rows saves work)")


def padded_vocab(vocab):
    """Vocab rounded up to a multiple of :data:`PAD_MULTIPLE`."""
    v = int(vocab)
    return -(-v // PAD_MULTIPLE) * PAD_MULTIPLE


def to_shard_major(table, num_shards):
    """Logical row order -> mod-interleaved storage order (host numpy).

    Storage row ``s*rps + k`` receives logical row ``k*n + s``; with
    ``num_shards == 1`` the layout is the identity."""
    n = int(num_shards)
    t = np.asarray(table)
    if n <= 1:
        return t
    if t.shape[0] % n:
        raise ValueError("table rows %d not divisible by %d shards"
                         % (t.shape[0], n))
    return np.ascontiguousarray(
        t.reshape((t.shape[0] // n, n) + t.shape[1:])
        .swapaxes(0, 1).reshape(t.shape))


def to_logical(table, num_shards):
    """Inverse of :func:`to_shard_major`."""
    n = int(num_shards)
    t = np.asarray(table)
    if n <= 1:
        return t
    if t.shape[0] % n:
        raise ValueError("table rows %d not divisible by %d shards"
                         % (t.shape[0], n))
    return np.ascontiguousarray(
        t.reshape((n, t.shape[0] // n) + t.shape[1:])
        .swapaxes(0, 1).reshape(t.shape))


def register_table(program, name, vocab, padded, dim, slot_of=None):
    """Record a distributed table (or one of its optimizer slots) on
    its program — the registry DistStrategy placement, the executor
    cache key, and checkpoint reshard all read."""
    tables = getattr(program, "_dist_embeddings", None)
    if tables is None:
        tables = {}
        program._dist_embeddings = tables
    tables[name] = {"vocab": int(vocab), "padded": int(padded),
                    "dim": int(dim), "slot_of": slot_of}


def dist_tables(program):
    """The program's distributed-table registry (or None)."""
    return getattr(program, "_dist_embeddings", None)


def active_shards(strategy, padded):
    """(num_shards, mesh, axis) the mod layout splits into under this
    strategy — 1/None/None whenever row sharding cannot apply (no
    strategy, ``embedding_shard_rows`` off, no data axis, or a shard
    count that doesn't divide the padded vocab). Storage layout,
    placement, and the traced ops all derive from this one rule, so
    they can never disagree within a run."""
    if strategy is None:
        return 1, None, None
    from .. import config as _config
    if not _config.get_flag("embedding_shard_rows"):
        return 1, None, None
    axis = strategy.data_axis
    if axis is None:
        return 1, None, None
    n = strategy.data_shards()
    if n <= 1 or int(padded) % n:
        return 1, None, None
    return n, strategy.mesh, axis


# -- traced routes -----------------------------------------------------

def _bucketize(flat, local_rows, n, sentinel):
    """Static-shape id bucketing: stable-sort ids by owning shard and
    lay shard s's ids at ``bucket[s, :counts[s]]`` (rest = sentinel).
    Returns (bucket [n, m], order [m], idx [n, m], valid [n, m]) — the
    same (order, idx, valid) reassemble replies or gradients."""
    m = flat.shape[0]
    owner = flat % n
    order = jnp.argsort(owner)  # jnp.argsort is stable
    sorted_local = local_rows[order]
    counts = jnp.bincount(owner, length=n)
    offs = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]])
    col = jnp.arange(m)
    idx = offs[:, None] + col[None, :]
    valid = col[None, :] < counts[:, None]
    bucket = jnp.where(valid, sorted_local[jnp.clip(idx, 0, m - 1)],
                       sentinel)
    return bucket, order, idx, valid


def _local_rows(flat, n, rps, pad):
    """Per-id local row within the owning shard; padding_idx ids are
    pushed to the out-of-range sentinel ``rps`` (their forward output
    is zeroed, their gradient dropped)."""
    local = flat // n
    if pad is not None:
        local = jnp.where(flat == pad, rps, local)
    return local


def _a2a_lookup(dim, mesh, axis, n, rps, wire=None):
    """Two-hop all_to_all lookup on the shard-major table. Local rows
    already carry the pad sentinel; sentinel/invalid slots come back
    as zero rows. ``wire="int8"`` quantizes rows SHARD-SIDE before the
    return hop (symmetric per-row amax/127 int8 + one f32 scale per
    row crosses the wire instead of f32 rows — ~3.9x fewer payload
    bytes at dim 128) and dequantizes after; zero/sentinel rows
    quantize to exactly zero, and the gradient route stays f32."""

    def f(w_loc, flat_loc, local_loc):
        m = flat_loc.shape[0]
        bucket, order, idx, valid = _bucketize(flat_loc, local_loc, n,
                                               rps)
        recv = jax.lax.all_to_all(bucket, axis, 0, 0)        # [n, m]
        rows = jnp.where((recv < rps)[..., None],
                         w_loc[jnp.clip(recv, 0, rps - 1)], 0.0)
        if wire == "int8":
            amax = jnp.max(jnp.abs(rows), axis=-1, keepdims=True)
            qscale = jnp.where(amax > 0.0, amax / 127.0,
                               jnp.ones_like(amax))
            qrows = jnp.clip(jnp.rint(rows / qscale), -127.0, 127.0) \
                .astype(jnp.int8)
            back = jax.lax.all_to_all(qrows, axis, 0, 0) \
                .astype(w_loc.dtype) \
                * jax.lax.all_to_all(qscale, axis, 0, 0)     # [n, m, D]
        else:
            back = jax.lax.all_to_all(rows, axis, 0, 0)      # [n, m, D]
        out_sorted = jnp.zeros((m + 1, dim), w_loc.dtype).at[
            jnp.where(valid, idx, m)].set(back, mode="drop")[:m]
        return jnp.zeros_like(out_sorted).at[order].set(out_sorted)

    from ..jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    return shard_map(
        f, mesh, in_specs=(P(axis, None), P(axis), P(axis)),
        out_specs=P(axis, None), check_vma=False)


def _a2a_grad(dim, axis, n, rps, vp):
    """Reverse route: output-row gradients travel to the owning shard,
    get merged per shard, and surface as (Rows, Values) in global
    shard-major coordinates (sentinels -> ``vp``, dropped by the
    optimizer scatter)."""
    from ..ops.sparse_ops import merge_duplicate_rows

    def f(g_loc, flat_loc, local_loc):
        m = flat_loc.shape[0]
        bucket, order, idx, valid = _bucketize(flat_loc, local_loc, n,
                                               rps)
        g_sorted = g_loc[order]
        bvals = jnp.where(valid[..., None],
                          g_sorted[jnp.clip(idx, 0, m - 1)], 0.0)
        rrows = jax.lax.all_to_all(bucket, axis, 0, 0)       # [n, m]
        rvals = jax.lax.all_to_all(bvals, axis, 0, 0)        # [n, m, D]
        s = jax.lax.axis_index(axis)
        grows = jnp.where(rrows < rps, rrows + s * rps, vp).reshape(-1)
        return merge_duplicate_rows(grows.astype(jnp.int32),
                                    rvals.reshape(-1, dim), vp)

    return f


def _trace_mode(flat_len, vp):
    """(n, mesh, axis, use_a2a, telemetry, wire) for the current trace
    — one place both ops read; with no strategy set (single device,
    program build-time shape inference) nothing reads any config flag.
    ``wire`` is the forward a2a payload dtype (embedding_wire_dtype,
    only consulted when the a2a route is live; gradients stay f32)."""
    from .. import parallel as _parallel
    strat = _parallel.current_strategy()
    if strat is None:
        return 1, None, None, False, False, None
    n, mesh, axis = active_shards(strat, vp)
    from .. import config as _config
    use_a2a = (n > 1 and bool(_config.get_flag("embedding_a2a"))
               and flat_len % n == 0)
    wire = None
    if use_a2a:
        w = _config.get_flag("embedding_wire_dtype")
        if w:
            wire = str(w)
    return (n, mesh, axis, use_a2a, bool(_config.get_flag("telemetry")),
            wire)


def _tel_record(unique, total=0, ids_bytes=0, rows_bytes=0,
                lookup=False):
    """Host callback target (jax.debug.callback): runs once per
    executed step, only when telemetry was armed at trace time."""
    if lookup:
        _LOOKUP_ROWS.inc(float(total))
        if total:
            _UNIQUE_RATIO.set(float(unique) / float(total))
    if ids_bytes:
        _A2A_BYTES.labels(direction="ids").inc(float(ids_bytes))
    if rows_bytes:
        _A2A_BYTES.labels(direction="rows").inc(float(rows_bytes))


def _unique_count(flat):
    if flat.shape[0] == 0:
        return jnp.zeros((), jnp.int32)
    s = jnp.sort(flat)
    return 1 + (s[1:] != s[:-1]).sum().astype(jnp.int32)


def a2a_step_bytes(total_ids, dim, n, itemsize=4, index_itemsize=4):
    """Static per-step exchange volume of one two-hop route, summed
    over devices: the index hop moves ``n * total_ids`` indices, the
    payload hop ``n * total_ids`` rows (bucket capacity = per-device id
    count, so each of the n devices ships n buckets of that size).
    Also the probe's printed comparison basis."""
    ids_b = n * total_ids * index_itemsize
    rows_b = n * total_ids * dim * itemsize
    return ids_b, rows_b


@register_op("lookup_table_dist")
def _lookup_table_dist_op(ctx):
    """Distributed embedding lookup on a mod-interleaved table."""
    w, ids = ctx.input("W"), ctx.input("Ids")
    vp = int(ctx.attr("padded_vocab"))
    pad = ctx.attr("padding_idx")
    squeeze = (not ctx.attr("keep_dims", False) and ids.shape
               and ids.shape[-1] == 1)
    ishape = tuple(ids.shape[:-1] if squeeze else ids.shape)
    dim = w.shape[1]
    flat = ids.reshape(-1).astype(jnp.int32)
    n, mesh, axis, use_a2a, telemetry, wire = _trace_mode(
        flat.shape[0], vp)
    rps = vp // n
    local = _local_rows(flat, n, rps, pad)
    if use_a2a:
        out = _a2a_lookup(dim, mesh, axis, n, rps, wire=wire)(
            w, flat, local)
    else:
        # identity layout (n == 1) or GSPMD-partitioned gather through
        # the mod layout (sharding on, a2a off)
        srow = jnp.clip((flat % n) * rps + local, 0, vp - 1)
        out = jnp.take(w, srow, axis=0)
        if pad is not None:
            out = jnp.where((flat == pad)[:, None], 0.0, out)
    if telemetry:
        total = int(flat.shape[0])
        if use_a2a:
            ids_b, rows_b = a2a_step_bytes(
                total, dim, n, itemsize=1 if wire == "int8" else 4)
            if wire == "int8":
                rows_b += n * total * 4  # per-row f32 scales, return hop
        else:
            ids_b, rows_b = 0, 0
        jax.debug.callback(
            functools.partial(_tel_record, total=total, ids_bytes=ids_b,
                              rows_bytes=rows_b, lookup=True),
            _unique_count(flat))
    return {"Out": out.reshape(ishape + (dim,))}


@register_op("lookup_table_dist_grad")
def _lookup_table_dist_grad_op(ctx):
    """d(lookup_table_dist)/dW as (Rows, Values) in global shard-major
    coordinates — never a dense [padded_vocab, dim] cotangent. In a2a
    mode each shard's received gradients are merged locally
    (merge_duplicate_rows) before the optimizer's global merge."""
    og, ids = ctx.input("OutGrad"), ctx.input("Ids")
    vp = int(ctx.attr("padded_vocab"))
    pad = ctx.attr("padding_idx")
    flat = ids.reshape(-1).astype(jnp.int32)
    dim = og.shape[-1]
    g = og.reshape(flat.shape[0], dim)
    n, mesh, axis, use_a2a, telemetry, _wire = _trace_mode(
        flat.shape[0], vp)
    rps = vp // n
    local = _local_rows(flat, n, rps, pad)
    if use_a2a:
        from ..jax_compat import shard_map
        from jax.sharding import PartitionSpec as P
        rows, vals = shard_map(
            _a2a_grad(dim, axis, n, rps, vp), mesh,
            in_specs=(P(axis, None), P(axis), P(axis)),
            out_specs=(P(axis), P(axis, None)),
            check_vma=False)(g, flat, local)
    else:
        rows = jnp.where(local >= rps, vp,
                         (flat % n) * rps + local).astype(jnp.int32)
        vals = g
    if telemetry and use_a2a:
        ids_b, rows_b = a2a_step_bytes(int(flat.shape[0]), dim, n)
        jax.debug.callback(
            functools.partial(_tel_record, ids_bytes=ids_b,
                              rows_bytes=rows_b),
            jnp.zeros((), jnp.int32))
    return {"Rows": rows, "Values": vals}
