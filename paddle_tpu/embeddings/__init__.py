"""Sharded embedding tables: row-sharded storage + ICI all-to-all
lookup/gradient exchange (sharded.py) and shard-layout-aware checkpoint
reshard (checkpoint.py) — the reference pserver capability (PAPER.md
§2) rebuilt inside the jitted step. Importing this package registers
the ``lookup_table_dist`` / ``lookup_table_dist_grad`` ops."""

from .sharded import (  # noqa: F401
    PAD_MULTIPLE, padded_vocab, to_shard_major, to_logical,
    register_table, dist_tables, active_shards, a2a_step_bytes)
from .checkpoint import (  # noqa: F401
    layout_meta, reshard_scope, reshard_array)

__all__ = ["PAD_MULTIPLE", "padded_vocab", "to_shard_major",
           "to_logical", "register_table", "dist_tables",
           "active_shards", "a2a_step_bytes", "layout_meta",
           "reshard_scope", "reshard_array"]
