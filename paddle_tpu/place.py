"""Places — device identities (reference ``paddle/platform/place.h:24-53``:
CPUPlace/CUDAPlace variant). TPU-native: TPUPlace is first-class; CUDAPlace
kept as an API-compat alias that resolves to whatever accelerator JAX has.
"""

import jax

__all__ = ["CPUPlace", "TPUPlace", "CUDAPlace", "is_compiled_with_tpu"]


class _Place:
    def __repr__(self):
        return self.__class__.__name__ + "()"

    def __eq__(self, other):
        return type(self) is type(other) and \
            getattr(self, "device_id", 0) == getattr(other, "device_id", 0)

    def __hash__(self):
        return hash((type(self).__name__, getattr(self, "device_id", 0)))


class CPUPlace(_Place):
    def jax_device(self):
        return jax.devices("cpu")[0]


class TPUPlace(_Place):
    def __init__(self, device_id=0):
        self.device_id = device_id

    def jax_device(self):
        devs = jax.devices()
        return devs[self.device_id % len(devs)]


class CUDAPlace(TPUPlace):
    """Compat alias: scripts written against the reference's CUDAPlace run
    on the default JAX accelerator."""


def is_compiled_with_tpu():
    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except RuntimeError:
        return False
