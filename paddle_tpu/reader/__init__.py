"""Reader decorators — push-based Python data pipelines.

Parity with reference ``python/paddle/v2/reader/decorator.py:51-236``:
shuffle, buffered, chain, compose, map_readers, batch, xmap_readers
(parallel map), firstn, cache. A reader is a zero-arg callable returning an
iterator of samples (reference contract kept verbatim).

TPU note: pair these with ``data_feeder.DataFeeder`` for batching/padding
and ``buffered`` for host-side prefetch that overlaps the device step (the
analog of the reference's async double-buffer DataProvider,
``dataproviders/DataProvider.h:375``).
"""

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["shuffle", "buffered", "chain", "compose", "map_readers",
           "batch", "xmap_readers", "firstn", "cache"]


def shuffle(reader, buf_size, seed=None):
    def reader_creator():
        rng = _random.Random(seed)
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf
    return reader_creator


def buffered(reader, size):
    """Background-thread prefetch queue (host/device overlap)."""
    end = object()

    def reader_creator():
        q = _queue.Queue(maxsize=size)

        def fill():
            try:
                for sample in reader():
                    q.put(sample)
            except Exception as e:  # surface in the consumer
                q.put(e)
            finally:
                q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            sample = q.get()
            if sample is end:
                break
            if isinstance(sample, Exception):
                # a reader failure (e.g. a generation-fenced dispatcher
                # raising GenerationMismatch) must not read as a clean
                # end-of-pass — re-raise where the train loop can see it
                raise sample
            yield sample
    return reader_creator


def chain(*readers):
    def reader_creator():
        for r in readers:
            yield from r()
    return reader_creator


def compose(*readers, check_alignment=True):
    """Zip readers into tuple samples (reference compose)."""
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader_creator():
        its = [r() for r in readers]
        for outputs in itertools.zip_longest(*its):
            if check_alignment and any(o is None for o in outputs):
                raise RuntimeError("composed readers have different "
                                   "lengths")
            yield sum((make_tuple(o) for o in outputs), ())
    return reader_creator


def map_readers(func, *readers):
    def reader_creator():
        for args in zip(*[r() for r in readers]):
            yield func(*args)
    return reader_creator


def batch(reader, batch_size, drop_last=True):
    def reader_creator():
        b = []
        for sample in reader():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return reader_creator


def xmap_readers(mapper, reader, process_num, buffer_size,
                 order=False):
    """Parallel map over samples with worker threads (reference
    xmap_readers)."""
    end = object()

    def reader_creator():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    break
                i, sample = item
                out_q.put((i, mapper(sample)))

        threads = [threading.Thread(target=feed, daemon=True)]
        threads += [threading.Thread(target=work, daemon=True)
                    for _ in range(process_num)]
        for t in threads:
            t.start()

        finished = 0
        pending = {}
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
                continue
            pending[item[0]] = item[1]
            while next_i in pending:
                yield pending.pop(next_i)
                next_i += 1
        if order:
            for i in sorted(pending):
                yield pending[i]
    return reader_creator


def firstn(reader, n):
    def reader_creator():
        return itertools.islice(reader(), n)
    return reader_creator


def cache(reader):
    all_data = []
    filled = []

    def reader_creator():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)
    return reader_creator
