"""Arena-staged input pipeline: host batch assembly -> device, overlapped.

The TPU-native analog of the reference's async double-buffer DataProvider
(``paddle/gserver/dataproviders/DataProvider.h:375``) and its pinned
staging buffers (``paddle/memory/memory.cc`` pinned path): a background
thread

1. pulls batches from the reader (through an optional ``DataFeeder``),
2. copies each array into a 64-byte-aligned block of the native buddy
   arena (``native/buddy_allocator.cc``) — stable host staging memory,
   the pinned-buffer analog,
3. dispatches ``jax.device_put`` (async H2D) and queues the ready feed,

so host batch assembly and H2D transfer overlap the device step that the
consumer is running. Arena blocks are recycled with a two-batch lag AND
only after the batch's device arrays report transfer-complete
(``block_until_ready`` on the staged arrays) — the lag keeps the arena
hot-path free of blocking in the steady state, the readiness barrier
guarantees no block is returned to the allocator while an asynchronous
H2D DMA may still be reading it. With ``device_put=False`` the arena is
not used at all: the consumer would hold live views into arena memory,
so plain (background-threaded) numpy copies are the staging path.

Falls back to plain numpy copies (still background-threaded) if the
native library is unavailable; ``arena_active`` reports which path is in
use.
"""

import collections
import ctypes
import itertools
import queue as _queue
import threading
import time

import numpy as np

from .. import config as _config
from ..core import ingest as _ingest
from ..core.framework import convert_dtype
from ..observability import metrics as _metrics
from ..observability import tracing as _tracing

__all__ = ["StagedReader"]

_END = object()

# Input-pipeline telemetry (recording gated by the "telemetry" flag):
# live queue/arena gauges in the registry replace the one-shot
# set_gauges snapshot the trainer used to take at teardown. Gauges are
# labeled per reader instance so concurrent StagedReaders don't
# clobber each other; the counter/histogram are additive and global.
_QUEUE_DEPTH = _metrics.REGISTRY.gauge(
    "paddle_staging_queue_depth",
    "Staged batches queued ahead of the consumer, per reader",
    labelnames=("reader",))
_STAGED_TOTAL = _metrics.REGISTRY.counter(
    "paddle_staging_batches_total", "Batches staged (all readers)")
_STAGE_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_staging_stage_seconds",
    "Per-batch staging time: reader pull + feeder + arena copy + "
    "device_put dispatch")
_ARENA_IN_USE = _metrics.REGISTRY.gauge(
    "paddle_staging_arena_in_use_bytes",
    "Buddy-arena bytes currently allocated to in-flight batches, "
    "per reader",
    labelnames=("reader",))
_ARENA_PEAK = _metrics.REGISTRY.gauge(
    "paddle_staging_arena_peak_bytes",
    "Buddy-arena high-water mark, per reader",
    labelnames=("reader",))
# Narrow-wire accounting: what actually crossed H2D vs what the legacy
# widened path would have moved, and how many device_put dispatches it
# took. bench_resnet_pipeline asserts exactly one dispatch per batch on
# the packed path via the transfers counter.
_WIRE_BYTES = _metrics.REGISTRY.counter(
    "paddle_staging_wire_bytes_total",
    "Bytes actually transferred host->device by staging")
_LEGACY_BYTES = _metrics.REGISTRY.counter(
    "paddle_staging_legacy_bytes_total",
    "Bytes the pre-wire path (widened dtypes, per-array device_put) "
    "would have transferred for the same batches")
_TRANSFERS = _metrics.REGISTRY.counter(
    "paddle_staging_h2d_transfers_total",
    "device_put dispatches issued by staging (packed path: one per "
    "batch per mesh shard)")
_SPARSE_SLOTS = _metrics.REGISTRY.counter(
    "paddle_staging_sparse_slots_total",
    "Ragged (ids, offsets, values) sparse slots carried on the packed "
    "wire — batches that would otherwise fall back to per-array H2D")
_READER_IDS = itertools.count(1)


class _Arena:
    """ctypes wrapper over one native buddy arena."""

    def __init__(self, capacity_bytes):
        from .. import native
        self._lib = native.arena_lib()
        self._handle = self._lib.ptarena_create(
            ctypes.c_size_t(capacity_bytes))
        if not self._handle:
            raise MemoryError("buddy arena creation failed")

    def alloc_array(self, shape, dtype, nbytes):
        ptr = self._lib.ptarena_alloc(self._handle,
                                      ctypes.c_size_t(nbytes))
        if not ptr:
            return None, None  # exhausted — caller falls back
        buf = (ctypes.c_char * nbytes).from_address(ptr)
        arr = np.frombuffer(buf, dtype=dtype).reshape(shape)
        return arr, ptr

    def free(self, ptr):
        self._lib.ptarena_free(self._handle, ctypes.c_void_p(ptr))

    def in_use(self):
        return int(self._lib.ptarena_in_use(self._handle))

    def peak(self):
        return int(self._lib.ptarena_peak(self._handle))

    def destroy(self):
        if self._handle:
            self._lib.ptarena_destroy(self._handle)
            self._handle = None


class StagedReader:
    """Drop-in reader: ``staged()`` yields ready-to-run feed dicts.

    reader: yields batches (lists of samples if ``feeder`` given, else
    feed dicts of numpy arrays).
    feeder: optional DataFeeder applied on the staging thread.
    depth: queue depth (batches staged ahead of the consumer).
    capacity_mb: arena size; a batch set larger than this falls back to
    plain numpy staging for the overflowing arrays.
    device_put: dispatch jax.device_put on the staging thread (H2D in
    flight before the consumer sees the feed).
    pack: pack each batch into ONE contiguous block and issue ONE
    device_put (core/ingest.py); the executor unpacks inside the
    compiled step. None (default) follows the ``packed_feeds`` config
    flag. Unpackable batches (ragged leading dims) fall back per-batch
    to the per-array path.
    strategy: a parallel.DistStrategy — packed batches are split on
    host over its data axis and scattered shard-per-device
    (jax.make_array_from_single_device_arrays), so a multi-chip feed
    costs one per-shard transfer each instead of a replicated
    full-batch transfer.
    """

    def __init__(self, reader, feeder=None, depth=2, capacity_mb=256,
                 device_put=True, free_lag=2, pack=None, strategy=None,
                 program=None):
        self.reader = reader
        self.feeder = feeder
        self.depth = max(1, int(depth))
        self.device_put = device_put
        self.free_lag = max(0, int(free_lag))
        self.pack = pack
        self.strategy = strategy
        self.program = program  # for feed-var dtype lookups (telemetry)
        self.packed_batches = 0
        # recent (stage_start, stage_end) windows; bounded — only the
        # overlap test and debugging read these
        self.records = collections.deque(maxlen=1024)
        self.staged_batches = 0
        self.arena_active = False
        self._tel_label = "r%d" % next(_READER_IDS)
        self._arena = None
        self._active = None    # (thread, stop, queue) of a live fill
        # The arena only serves the device_put path: each block is read
        # once by the H2D DMA and recycled after transfer-complete.
        # Without device_put the consumer would hold live views INTO
        # arena memory, making any recycle (or arena destroy) a silent
        # corruption — plain numpy copies are the correct staging there.
        if device_put:
            try:
                self._arena = _Arena(int(capacity_mb) * (1 << 20))
                self.arena_active = True
            except Exception:
                self._arena = None

    # -- stats ----------------------------------------------------------
    def stats(self):
        s = {"staged_batches": self.staged_batches,
             "packed_batches": self.packed_batches,
             "arena_active": self.arena_active}
        if self._arena is not None:
            s["arena_peak_bytes"] = self._arena.peak()
            s["arena_in_use_bytes"] = self._arena.in_use()
        return s

    def packing_enabled(self):
        return self.device_put and (
            self.pack if self.pack is not None
            else bool(_config.get_flag("packed_feeds")))

    # -- staging thread --------------------------------------------------
    def _legacy_nbytes(self, name, arr):
        """What the pre-wire path would have moved for this array: the
        wider of its original width and the var's model dtype (a uint8
        wire image would have crossed as f32; an int64 label crossed as
        int64 before the host canon to int32)."""
        nbytes = arr.nbytes
        var = self._feed_var(name)
        if var is not None:
            try:
                tgt = np.dtype(convert_dtype(var.dtype))
                nbytes = max(nbytes, arr.size * tgt.itemsize)
            except TypeError:
                pass  # bf16 scalar-type target: keep original width
        return nbytes

    def _feed_var(self, name):
        from ..core.framework import Variable
        if self.feeder is not None:
            for kind, var, len_var in self.feeder.feed_specs:
                for v in (var, len_var):
                    if isinstance(v, Variable) and v.name == name:
                        return v
        if self.program is not None:
            return self.program.global_block().var_or_none(name)
        return None

    def _stage_packed(self, feed):
        """Fused single-copy path: one arena block, one device_put (one
        per mesh shard under a data-parallel strategy). Returns
        (PackedBatch, ptrs) or None to fall back."""
        shards = self.strategy.data_shards() \
            if self.strategy is not None else 1

        def alloc(n):
            if self._arena is None:
                return None, None
            return self._arena.alloc_array((n,), np.uint8, n)

        packed = _ingest.pack_feed(feed, shards=shards, alloc=alloc)
        if packed is None:
            return None
        pb, ptr = packed
        telemetry = _config.get_flag("telemetry")
        if telemetry:
            _LEGACY_BYTES.inc(sum(
                self._legacy_nbytes(n, np.asarray(v))
                for n, v in feed.items()
                if not isinstance(v, _ingest.SparseTriple)))
            n_sparse = sum(1 for s in pb.layout if s.kind == "sparse")
            if n_sparse:
                _SPARSE_SLOTS.inc(n_sparse)
        if self.device_put:
            import jax
            if self.strategy is not None:
                # scatter_packed places on the mesh even when the data
                # axis is trivial (replicated) — a single-device-placed
                # buffer would collide with mesh-sharded state inputs
                pb.buffer, n_put = self.strategy.scatter_packed(pb.buffer)
            else:
                pb.buffer, n_put = jax.device_put(pb.buffer), 1
            # Transfer-completion barrier ON the staging thread: the
            # executor donates the device buffer, so nobody may touch
            # it after the step — completing the DMA here is what keeps
            # the arena recycle (and free_lag=0) safe under donation.
            jax.block_until_ready(pb.buffer)
            pb.transfer_done = True
            if telemetry:
                _WIRE_BYTES.inc(pb.nbytes)
                _TRANSFERS.inc(n_put)
        self.packed_batches += 1
        return pb, ([ptr] if ptr is not None else [])

    def _stage_feed(self, feed):
        """Copy arrays into arena blocks; returns (staged_feed, ptrs)."""
        if isinstance(feed, _ingest.PackedBatch):
            return feed, []  # reader yielded a pre-packed batch
        if self.packing_enabled():
            out = self._stage_packed(feed)
            if out is not None:
                return out
        # per-array fallback: ragged sparse triples become their three
        # cap-padded named arrays (core/ingest.explode_sparse)
        feed = _ingest.explode_sparse(feed)
        telemetry = _config.get_flag("telemetry")
        staged, ptrs = {}, []
        for name, value in feed.items():
            arr = np.asarray(value)
            if self._arena is not None and arr.nbytes > 0:
                dst, ptr = self._arena.alloc_array(arr.shape, arr.dtype,
                                                   arr.nbytes)
            else:
                dst, ptr = None, None
            if dst is None:
                dst = np.array(arr, copy=True)  # fallback staging
            else:
                np.copyto(dst, arr)
                ptrs.append(ptr)
            if self.device_put:
                import jax
                dst = jax.device_put(dst)
                if telemetry:
                    _WIRE_BYTES.inc(arr.nbytes)
                    _LEGACY_BYTES.inc(self._legacy_nbytes(name, arr))
                    _TRANSFERS.inc()
            staged[name] = dst
        return staged, ptrs

    @staticmethod
    def _wait_transfers(staged):
        """Block until the batch's H2D transfers are done (device path).
        numpy entries (device_put=False or fallback staging) pass
        through — they have no in-flight DMA."""
        import jax
        if isinstance(staged, _ingest.PackedBatch):
            if not staged.transfer_done and \
                    not isinstance(staged.buffer, np.ndarray):
                try:
                    jax.block_until_ready(staged.buffer)
                except RuntimeError:
                    pass  # donated to a step that already consumed it
            return
        arrays = [v for v in staged.values()
                  if not isinstance(v, np.ndarray)]
        if arrays:
            jax.block_until_ready(arrays)

    def _fill(self, q, stop):
        try:
            it = iter(self.reader())
            while not stop.is_set():
                t0 = time.perf_counter()  # window includes reader pull
                try:
                    batch = next(it)
                except StopIteration:
                    break
                with _tracing.span("stageBatch"):
                    feed = self.feeder.feed(batch) if self.feeder \
                        else batch
                    staged, ptrs = self._stage_feed(feed)
                t1 = time.perf_counter()
                self.records.append((t0, t1))
                self.staged_batches += 1
                if _config.get_flag("telemetry"):
                    _STAGED_TOTAL.inc()
                    _STAGE_SECONDS.observe(t1 - t0)
                    self._update_gauges(q)
                q.put((staged, ptrs))
        except Exception as e:  # surface in the consumer
            q.put(e)
        finally:
            q.put(_END)

    def _update_gauges(self, q):
        _QUEUE_DEPTH.labels(reader=self._tel_label).set(q.qsize())
        if self._arena is not None:
            _ARENA_IN_USE.labels(reader=self._tel_label).set(
                self._arena.in_use())
            _ARENA_PEAK.labels(reader=self._tel_label).set(
                self._arena.peak())

    # -- consumer --------------------------------------------------------
    def __call__(self):
        q = _queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        t = threading.Thread(target=self._fill, args=(q, stop),
                             daemon=True)
        self._active = (t, stop, q)
        t.start()
        pending = collections.deque()  # ptr lists awaiting recycle
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                if isinstance(item, Exception):
                    raise item
                staged, ptrs = item
                if _config.get_flag("telemetry"):
                    self._update_gauges(q)
                # recycle arena blocks free_lag batches behind, and only
                # once the batch's own H2D transfers have completed — the
                # lag keeps this non-blocking in steady state, the
                # readiness barrier makes the free safe under
                # backpressure (ptrs is empty when the arena is off).
                pending.append((ptrs, staged))
                while len(pending) > self.free_lag + 1:
                    old_ptrs, old_staged = pending.popleft()
                    if old_ptrs:
                        self._wait_transfers(old_staged)
                        for p in old_ptrs:
                            self._arena.free(p)
                yield staged
        finally:
            self._shutdown(t, stop, q, pending)

    def _shutdown(self, t, stop, q, pending):
        """Stop + JOIN the fill thread, then recycle every arena block.
        The join makes a subsequent close() (arena destroy) safe — no
        producer can be mid-copy into arena memory afterwards."""
        stop.set()
        # drain so a producer blocked on q.put can observe stop and exit
        while t.is_alive():
            try:
                item = q.get_nowait()
                if isinstance(item, tuple):
                    pending.append((item[1], item[0]))
            except _queue.Empty:
                pass
            t.join(timeout=0.05)
        try:
            while True:
                item = q.get_nowait()
                if isinstance(item, tuple):
                    pending.append((item[1], item[0]))
        except _queue.Empty:
            pass
        self._active = None
        if self._arena is not None:
            for ptrs, staged in pending:
                if ptrs and staged is not None:
                    try:  # transfer-completion barrier before recycling
                        self._wait_transfers(staged)
                    except Exception:
                        pass
                for p in ptrs:
                    self._arena.free(p)

    def close(self):
        if self._active is not None:
            # consumer abandoned the generator mid-pass (exception /
            # interrupt): shut the producer down before freeing memory
            t, stop, q = self._active
            self._shutdown(t, stop, q, collections.deque())
        if self._arena is not None:
            import jax
            try:
                # a suspended generator frame may still hold batches in
                # its local pending deque, unreachable from here; their
                # device_put DMAs must finish before the arena unmaps
                jax.effects_barrier()
            except Exception:
                pass
            self._arena.destroy()
            self._arena = None
            self.arena_active = False
