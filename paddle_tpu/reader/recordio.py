"""RecordIO file reader/writer + native shuffling loader.

Python face of the native data layer (native/recordio.cc,
native/shuffle_pool.cc). Parity: reference ``reader/creator.py`` recordio
readers + ``dataset/common.convert`` writer + the C++-side sample pool of
PyDataProvider2 (SURVEY B.7) — here the pool and chunk IO are C++, the
decode is a user Python function, and samples arrive pre-shuffled.
"""

import ctypes
import pickle
import threading

from .. import native

__all__ = ["write_recordio", "read_recordio", "chunked_reader",
           "ShuffleLoader", "recordio_reader"]


def write_recordio(path, samples, max_chunk_bytes=1 << 20,
                   serialize=pickle.dumps):
    """Write an iterable of samples to a RecordIO file; returns count."""
    lib = native.recordio_lib()
    h = lib.ptrc_writer_open(path.encode(), max_chunk_bytes)
    if not h:
        raise IOError("cannot open %s for writing" % path)
    n = 0
    try:
        for s in samples:
            data = serialize(s)
            if lib.ptrc_writer_write(h, data, len(data)) != 0:
                raise IOError("write failed at record %d" % n)
            n += 1
    finally:
        lib.ptrc_writer_close(h)
    return n


class _Reader:
    def __init__(self, path):
        self.lib = native.recordio_lib()
        self.h = self.lib.ptrc_reader_open(path.encode())
        if not self.h:
            raise IOError("cannot open %s" % path)

    def num_chunks(self):
        return self.lib.ptrc_reader_num_chunks(self.h)

    def chunk_records(self, i):
        n = self.lib.ptrc_reader_load_chunk(self.h, i)
        if n < 0:
            raise IOError("bad chunk %d" % i)
        out = []
        for _ in range(n):
            ln = self.lib.ptrc_reader_peek_len(self.h)
            buf = ctypes.create_string_buffer(ln)
            self.lib.ptrc_reader_next(self.h, buf, ln)
            out.append(buf.raw)
        return out

    def close(self):
        if self.h:
            self.lib.ptrc_reader_close(self.h)
            self.h = None


def read_recordio(path, deserialize=pickle.loads):
    """Reader creator over all records of a file."""
    def reader():
        r = _Reader(path)
        try:
            for i in range(r.num_chunks()):
                for rec in r.chunk_records(i):
                    yield deserialize(rec)
        finally:
            r.close()
    return reader


def chunked_reader(path, chunk_indices, deserialize=pickle.loads):
    """Reader over SPECIFIC chunks — the task-dispatch granularity used
    with the elastic master (distributed/master.py)."""
    def reader():
        r = _Reader(path)
        try:
            for i in chunk_indices:
                for rec in r.chunk_records(i):
                    yield deserialize(rec)
        finally:
            r.close()
    return reader


def num_chunks(path):
    r = _Reader(path)
    try:
        return r.num_chunks()
    finally:
        r.close()


class ShuffleLoader:
    """Native shuffling prefetch pool fed by a background thread.

    loader = ShuffleLoader(reader, min_pool=1024); for s in loader: ...
    """

    def __init__(self, reader, min_pool=1024, max_pool=0, seed=0,
                 serialize=pickle.dumps, deserialize=pickle.loads):
        self.lib = native.shuffle_pool_lib()
        self.h = self.lib.ptpool_create(min_pool, max_pool, seed)
        self.deserialize = deserialize

        def produce():
            try:
                for s in reader():
                    data = serialize(s)
                    if self.lib.ptpool_push(self.h, data, len(data)) != 0:
                        break
            finally:
                self.lib.ptpool_close(self.h)

        self._thread = threading.Thread(target=produce, daemon=True)
        self._thread.start()

    def __iter__(self):
        cap = 1 << 16
        buf = ctypes.create_string_buffer(cap)
        while True:
            n = self.lib.ptpool_pop(self.h, buf, cap)
            if n == -1:
                break
            if n < -1:  # -(len+1): buffer too small, record not consumed
                cap = -n
                buf = ctypes.create_string_buffer(cap)
                continue
            yield self.deserialize(buf.raw[:n])

    def __del__(self):
        try:
            self.lib.ptpool_destroy(self.h)
        except Exception:
            pass


def recordio_reader(path, shuffle_pool=0, seed=0):
    """Convenience: recordio file -> (optionally pool-shuffled) reader."""
    base = read_recordio(path)
    if not shuffle_pool:
        return base

    def reader():
        return iter(ShuffleLoader(base, min_pool=shuffle_pool, seed=seed))
    return reader
