"""Distributed runtime: elastic control plane + multi-host launch.

Replaces the reference's distribution stack per SURVEY §5.8:
* data-plane collectives: jax.sharding + SPMD (see paddle_tpu.parallel) —
  not here; XLA emits them.
* control plane: native/task_master.cc (C++ daemon) with the Python client
  in master.py — go/master parity (task leases, timeout requeue, failure
  budget, snapshot recovery).
* multi-host bring-up: launch.py wraps jax.distributed.initialize (the
  jax.distributed runtime replaces pserver endpoints/etcd discovery).
"""

from .master import MasterServer, MasterClient, ElasticDataDispatcher  # noqa
from .launch import init_multihost  # noqa: F401
