"""Distributed runtime: elastic control plane + multi-host launch.

Replaces the reference's distribution stack per SURVEY §5.8:
* data-plane collectives: jax.sharding + SPMD (see paddle_tpu.parallel) —
  not here; XLA emits them.
* control plane: native/task_master.cc (C++ daemon) with the Python client
  in master.py — go/master parity (task leases, timeout requeue, failure
  budget, snapshot recovery).
* multi-host bring-up: launch.py wraps jax.distributed.initialize (the
  jax.distributed runtime replaces pserver endpoints/etcd discovery).
* elastic membership: elastic.py — heartbeat-tracked cluster
  generations over the master's REG/HB protocol, hang-free collective
  abort, and the ElasticTrainerLoop that resumes training on a resized
  mesh after a peer death (go/master re-lease + etcd membership,
  joined).
"""

from .master import (MasterServer, MasterClient,  # noqa: F401
                     ElasticDataDispatcher, GenerationMismatch)
from .launch import (init_multihost, shutdown_multihost,  # noqa: F401
                     multihost_active)
from .elastic import (ElasticTrainerLoop, ElasticWorld,  # noqa: F401
                      MembershipHeartbeat, ElasticRestartLimit,
                      collective_abort)
