"""Multi-host bring-up.

Replaces the reference's cluster bootstrap (pserver endpoints lists,
etcd discovery, trainer_id/num_gradient_servers gflags —
``paddle/utils/Flags.cpp``, ``go/pserver/etcd_client.go``) with the JAX
distributed runtime: one coordinator address, process_id/num_processes,
then global devices participate in one SPMD mesh over ICI/DCN.
"""

import math
import os

__all__ = ["init_multihost", "shutdown_multihost", "multihost_active"]

# Whether THIS module initialized jax.distributed (so shutdown_multihost
# and elastic re-init know there is something to tear down).
_active = False


def multihost_active():
    return _active


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None, initialization_timeout_sec=None):
    """Initialize jax.distributed from args or the standard env vars
    (PADDLE_TPU_COORDINATOR / PADDLE_TPU_NUM_PROCS / PADDLE_TPU_PROC_ID).
    On a single process this is a no-op. Returns (process_id,
    num_processes).

    ``initialization_timeout_sec`` (or env PADDLE_TPU_INIT_TIMEOUT)
    bounds how long the rendezvous waits for the coordinator and peers;
    on expiry a RuntimeError names the coordinator address instead of
    the opaque hang/stack the raw initialize produces. Invalid
    process_id/num_processes combinations are rejected up front — a
    worker launched with process_id >= num_processes would otherwise
    wedge every OTHER worker's rendezvous until their timeout."""
    global _active
    import jax
    coordinator_address = coordinator_address or \
        os.environ.get("PADDLE_TPU_COORDINATOR")
    if coordinator_address is None:
        return 0, 1
    num_processes = int(num_processes if num_processes is not None else
                        os.environ.get("PADDLE_TPU_NUM_PROCS", "1"))
    process_id = int(process_id if process_id is not None else
                     os.environ.get("PADDLE_TPU_PROC_ID", "0"))
    if num_processes < 1:
        raise ValueError("num_processes must be >= 1, got %d"
                         % num_processes)
    if not 0 <= process_id < num_processes:
        raise ValueError(
            "process_id %d out of range for num_processes %d "
            "(valid: 0..%d)" % (process_id, num_processes,
                                num_processes - 1))
    if initialization_timeout_sec is None:
        env = os.environ.get("PADDLE_TPU_INIT_TIMEOUT")
        initialization_timeout_sec = float(env) if env else None
    kwargs = {}
    if initialization_timeout_sec is not None:
        # round UP: int() would turn a sub-second bound into 0, which
        # jax treats as already expired
        kwargs["initialization_timeout"] = \
            max(1, math.ceil(float(initialization_timeout_sec)))
    try:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                **kwargs)
        except TypeError:
            # older jax without initialization_timeout: retry without
            # the bound rather than fail bring-up over a tuning kwarg
            # (still inside the enriching wrapper, so a rendezvous
            # failure on the retry names the coordinator too)
            if not kwargs:
                raise
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
    except Exception as e:
        raise RuntimeError(
            "jax.distributed.initialize failed for process %d/%d "
            "against coordinator %s%s: %s — check that the coordinator "
            "process is up, the address is reachable, and every worker "
            "was launched with a distinct process_id"
            % (process_id, num_processes, coordinator_address,
               " (timeout %ss)" % initialization_timeout_sec
               if initialization_timeout_sec is not None else "",
               e)) from e
    _active = True
    return process_id, num_processes


def shutdown_multihost():
    """Tear down the jax.distributed runtime if this process brought it
    up (idempotent, exception-safe): the collective-abort primitive the
    elastic runtime calls before re-initializing at a new world size."""
    global _active
    if not _active:
        return False
    import jax
    try:
        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001 — teardown of a wedged runtime
        pass
    _active = False
    return True
