"""Multi-host bring-up.

Replaces the reference's cluster bootstrap (pserver endpoints lists,
etcd discovery, trainer_id/num_gradient_servers gflags —
``paddle/utils/Flags.cpp``, ``go/pserver/etcd_client.go``) with the JAX
distributed runtime: one coordinator address, process_id/num_processes,
then global devices participate in one SPMD mesh over ICI/DCN.
"""

import os

__all__ = ["init_multihost"]


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None):
    """Initialize jax.distributed from args or the standard env vars
    (PADDLE_TPU_COORDINATOR / PADDLE_TPU_NUM_PROCS / PADDLE_TPU_PROC_ID).
    On a single process this is a no-op. Returns (process_id,
    num_processes)."""
    import jax
    coordinator_address = coordinator_address or \
        os.environ.get("PADDLE_TPU_COORDINATOR")
    if coordinator_address is None:
        return 0, 1
    num_processes = int(num_processes or
                        os.environ.get("PADDLE_TPU_NUM_PROCS", "1"))
    process_id = int(process_id if process_id is not None else
                     os.environ.get("PADDLE_TPU_PROC_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)
    return process_id, num_processes
