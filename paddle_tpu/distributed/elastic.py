"""Elastic multi-host training runtime.

The reference stack survives worker churn with go/master chunk
re-leasing plus etcd membership (PAPER.md §2, §5.8). This module is
that story rebuilt for the jax runtime, in three pieces:

* **Membership** — workers ``register`` with the task master and a
  :class:`MembershipHeartbeat` thread beats on a background cadence.
  The master (native/task_master.cc) declares a worker dead after a
  missed-heartbeat deadline, bumps the cluster *generation*, and
  re-leases the dead worker's data chunks immediately. Survivors learn
  about the resize when their next beat comes back ``GENMISMATCH``.
* **Hang-free abort** — a SIGKILLed peer leaves survivors wedged inside
  an ICI all-reduce with no timeout. The resilience
  :class:`~paddle_tpu.resilience.supervisor.StepWatchdog` escalates an
  overrun step through ``on_hang`` -> :func:`collective_abort`
  (``jax.distributed.shutdown()`` + abandon in-flight dispatch) and the
  abort unwinds the train loop, bounded by ``step_deadline_sec``.
* **Resume on a resized mesh** — :class:`ElasticTrainerLoop` then
  re-registers at the new generation, re-runs ``init_multihost`` with
  the surviving world size, rebuilds the trainer (mesh + DistStrategy
  at the new size, via the caller's ``build`` factory), restores the
  newest intact checkpoint through the digest-verified fallback path
  (PR 3), re-syncs the LR scheduler and the dataset position (the
  master's lease table IS the dataset position), and resumes training.

A lost host becomes a bounded-time restore instead of a hung job.
Every transition is visible through the always-on ``paddle_elastic_*``
metrics. Deterministic chaos comes from the ``worker_kill`` /
``heartbeat_drop`` / ``collective_hang`` fault sites
(resilience/faults.py); the subprocess proving ground is
``tests/test_elastic.py`` + ``tools/multihost_chaos_probe.py``.

With the elasticity machinery unused (no ElasticTrainerLoop, default
flags) nothing here touches the train path: single-process behavior is
byte-identical.
"""

import os
import threading
import time

from .. import config as _config
from ..observability import metrics as _metrics
from ..resilience import faults as _faults
from ..utils import log as _log
from .launch import init_multihost, shutdown_multihost
from .master import GenerationMismatch, MasterClient

__all__ = ["ElasticTrainerLoop", "ElasticWorld", "MembershipHeartbeat",
           "ElasticRestartLimit", "collective_abort"]

# Recovery counters: always-on (they move on rare events, not per step).
_GENERATION = _metrics.REGISTRY.gauge(
    "paddle_elastic_generation",
    "This worker's view of the cluster membership generation")
_WORKER_DEATHS = _metrics.REGISTRY.counter(
    "paddle_elastic_worker_deaths_total",
    "Peer deaths observed via master generation bumps")
_RESUME_SECONDS = _metrics.REGISTRY.histogram(
    "paddle_elastic_resume_seconds",
    "Restart-trigger to restored-and-ready latency: re-register + "
    "runtime rebuild + digest-verified checkpoint restore")
_RESTARTS = _metrics.REGISTRY.counter(
    "paddle_elastic_restarts_total",
    "Elastic runtime teardown/rebuild cycles on this worker")
_HEARTBEATS = _metrics.REGISTRY.counter(
    "paddle_elastic_heartbeats_total", "Membership heartbeats sent")
_HB_MISSES = _metrics.REGISTRY.counter(
    "paddle_elastic_heartbeat_misses_total",
    "Heartbeats that failed to reach the master (connection errors)")
# resting value so scrapers see the family before the first bring-up
# (0 = this process has not joined a cluster)
_GENERATION.set(0)


class ElasticRestartLimit(RuntimeError):
    """The elastic loop exceeded its restart budget — the job is
    flapping (e.g. the master keeps resizing under it), not healing."""


# Per-master last-seen deaths, shared by every observer in this process
# (heartbeat threads AND trainer loops), so one peer death increments
# paddle_elastic_worker_deaths_total exactly once no matter which path
# noticed it first.
_deaths_seen = {}
_deaths_lock = threading.Lock()


def _observe_deaths(client):
    """Fold the master's authoritative cumulative deaths count into the
    local counter as a delta. The first observation of a master only
    sets the baseline — deaths that predate this process joining are
    not events it witnessed."""
    try:
        deaths = client.cluster()["deaths"]
    except (ConnectionError, OSError, ValueError, IndexError):
        return
    with _deaths_lock:
        last = _deaths_seen.get(client.addr)
        _deaths_seen[client.addr] = deaths
        if last is not None and deaths > last:
            _WORKER_DEATHS.inc(deaths - last)


def collective_abort(reason=""):
    """Tear down a (possibly wedged) distributed runtime so this
    process can re-initialize at a new world size.

    ``jax.distributed.shutdown()`` severs the coordination channel —
    in-flight cross-host collectives are abandoned rather than waited
    on (there is no cancel; the peers are gone). In-flight local
    dispatch is abandoned with it: arrays and executables built against
    the old global mesh are invalid at the new world size, so the
    restart path drops every reference (the rebuilt Executor re-places
    state under the new strategy, which also keys fresh compile-cache
    entries). Safe to call from any thread, idempotent, never raises.
    """
    _log.structured("elastic_collective_abort", reason=reason)
    return shutdown_multihost()


class ElasticWorld:
    """What a build factory gets to size the runtime by: the membership
    view at bring-up plus the handles it needs to wire a dispatcher."""

    def __init__(self, generation, n_live, worker_id, client,
                 process_id=0, num_processes=1):
        self.generation = generation
        self.n_live = n_live
        self.worker_id = worker_id
        self.client = client          # main-thread MasterClient
        self.process_id = process_id
        self.num_processes = num_processes

    def __repr__(self):
        return ("ElasticWorld(gen=%d, live=%d, worker=%r, proc=%d/%d)"
                % (self.generation, self.n_live, self.worker_id,
                   self.process_id, self.num_processes))


class MembershipHeartbeat:
    """Background liveness beats against the task master.

    Owns its own :class:`MasterClient` (clients are not thread-safe).
    On ``GENMISMATCH`` — a peer died and the master resized, or a
    restarted master forgot us — it re-registers at the current
    generation and fires ``on_change(old_gen, new_gen, n_live)`` so the
    runtime can escalate (typically ``trainer.request_restart``).
    Connection errors are absorbed (the master may be restarting;
    counted in ``paddle_elastic_heartbeat_misses_total``). The
    ``heartbeat_drop`` fault site swallows beats, which is how chaos
    tests force a master-declared death of a live process.
    """

    def __init__(self, port, worker_id, generation, host="127.0.0.1",
                 interval_sec=None, on_change=None):
        self.worker_id = worker_id
        self.generation = generation
        self.interval = (interval_sec if interval_sec is not None else
                         _config.get_flag("elastic_heartbeat_interval_sec"))
        self.on_change = on_change
        self._client = MasterClient(port, host=host, retries=2,
                                    backoff=0.05)
        self._stop_evt = threading.Event()
        self._thread = None
        self._beats = 0

    def start(self):
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="paddle-elastic-heartbeat")
        self._thread.start()
        return self

    def stop(self):
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self):
        _observe_deaths(self._client)  # baseline for the delta
        while not self._stop_evt.wait(self.interval):
            self._beats += 1
            if _faults.should_fire("heartbeat_drop", self._beats):
                continue  # injected network partition: beat never sent
            try:
                self._client.heartbeat(self.worker_id, self.generation)
                _HEARTBEATS.inc()
            except GenerationMismatch:
                self._rejoin()
            except (ConnectionError, OSError):
                _HB_MISSES.inc()

    def _rejoin(self):
        try:
            new_gen, n_live = self._client.register(self.worker_id)
        except (ConnectionError, OSError):
            _HB_MISSES.inc()
            return
        old = self.generation
        if new_gen == old:
            return  # master restart re-registration; membership as was
        self.generation = new_gen
        _GENERATION.set(new_gen)
        _observe_deaths(self._client)
        _log.structured("elastic_generation_change", old=old,
                        new=new_gen, live=n_live,
                        worker=self.worker_id)
        if self.on_change is not None:
            try:
                self.on_change(old, new_gen, n_live)
            except Exception:  # noqa: BLE001 — the beat must go on
                _log.logger().warning(
                    "elastic on_change callback failed", exc_info=True)


class ElasticTrainerLoop:
    """Run a training job that survives peer churn.

    ``build(world)`` is the caller's factory: given an
    :class:`ElasticWorld` it returns ``(trainer, reader)`` — a trainer
    (typically a ResilientTrainer with ``checkpoint_dir`` set and a
    ``step_deadline_sec`` watchdog) and the pass reader (typically an
    :class:`~paddle_tpu.distributed.master.ElasticDataDispatcher`
    reader fenced with ``world.generation``). The factory runs once per
    generation, so mesh/DistStrategy/trainer are rebuilt at every
    resize; checkpoint restore comes from ``trainer.startup()`` —
    the PR-3 digest-verified newest-intact path.

    Restart triggers, all funneled into one teardown/rebuild cycle:

    * the heartbeat thread sees a generation bump ->
      ``trainer.request_restart`` (in-flight step finishes, loop exits
      at the step boundary with a restart record);
    * the step watchdog aborts a hung step (wedged collective) ->
      KeyboardInterrupt unwinds ``train`` after ``on_hang`` ran
      :func:`collective_abort`;
    * a fenced master call raises :class:`GenerationMismatch`.

    Every bring-up starts with a membership **rendezvous**: the first
    one blocks until ``min_workers`` (default: ``num_processes``, the
    launch plan) have joined, restarts take whoever is live; the world
    is then sized from one atomic ``MEMBERS`` snapshot, with ranks in
    sorted-worker_id order — consistent across workers because any
    membership change bumps the generation and fences stale views
    into a rebuild.

    With ``coordinator_address`` set, each bring-up re-runs
    ``init_multihost`` (after :func:`collective_abort` tore the old
    runtime down) with the SURVIVING world size and this worker's
    membership rank, so the global mesh re-forms at the new size. jax
    requires rank 0 on the coordinator host: name that host's worker
    so it sorts first (e.g. ``w0``), and note that losing it — like
    losing the master — is not survivable. Without a coordinator
    (single-host / local chaos harness), the loop is the same
    choreography over local devices.
    """

    def __init__(self, build, master_port, worker_id=None,
                 master_host="127.0.0.1", heartbeat_interval_sec=None,
                 max_restarts=None, coordinator_address=None,
                 num_processes=None,
                 initialization_timeout_sec=None, min_workers=None,
                 rendezvous_timeout_sec=120.0,
                 master_reconnect_sec=30.0):
        self.build = build
        self.master_port = master_port
        self.master_host = master_host
        self.worker_id = worker_id or "w-%d" % os.getpid()
        self.heartbeat_interval_sec = heartbeat_interval_sec
        self.max_restarts = (max_restarts if max_restarts is not None
                             else _config.get_flag("elastic_max_restarts"))
        self.coordinator_address = coordinator_address
        self.num_processes = num_processes
        # NOTE: no process_id here — the jax rank is recomputed at every
        # bring-up from the settled membership (sorted-worker_id order),
        # so a caller-pinned rank would be wrong after the first resize
        self.initialization_timeout_sec = initialization_timeout_sec
        # first-bring-up rendezvous quorum: wait for the launch plan to
        # fully join before building, so concurrently starting workers
        # agree on the world instead of each building at a different
        # n_live. Defaults to num_processes (the plan) when given.
        self.min_workers = (min_workers if min_workers is not None
                            else (num_processes or 1))
        self.rendezvous_timeout_sec = rendezvous_timeout_sec
        self.master_reconnect_sec = master_reconnect_sec
        self.restarts = 0
        self.generations = []   # every generation this worker joined
        self._client = MasterClient(master_port, host=master_host)
        # set by the on_hang escalation (watchdog thread) so the loop
        # can tell a watchdog abort from a user Ctrl-C — both arrive
        # as KeyboardInterrupt, but only the former should restart
        self._hang_abort = False

    # -- bring-up ---------------------------------------------------------
    def _register_with_retry(self):
        """Register, absorbing a restarting master for up to
        ``master_reconnect_sec`` (the steady-state heartbeat path
        absorbs the same outage; bring-up must not be the one moment a
        master restart is fatal)."""
        deadline = time.monotonic() + self.master_reconnect_sec
        while True:
            try:
                return self._client.register(self.worker_id)
            except (ConnectionError, OSError):
                if time.monotonic() >= deadline:
                    raise
                _HB_MISSES.inc()
                time.sleep(0.5)

    def _rendezvous(self):
        """Register, then wait for a consistent membership snapshot:
        the first bring-up blocks until ``min_workers`` have joined
        (the launch plan), restarts just take whoever is live. Returns
        (generation, sorted member ids) — one MEMBERS response, so the
        view is atomic; any membership change after it bumps the
        generation and the fence forces a rebuild rather than letting
        two workers build different-sized worlds."""
        gen, _ = self._register_with_retry()
        min_live = self.min_workers if not self.generations else 1
        deadline = time.monotonic() + self.rendezvous_timeout_sec
        while True:
            try:
                mgen, members = self._client.members()
            except (ConnectionError, OSError):
                gen, _ = self._register_with_retry()
                continue
            if mgen != gen or self.worker_id not in members:
                # a join/death moved the cluster under us (or a
                # restarted master forgot us): adopt the new generation
                gen, _ = self._register_with_retry()
                continue
            if len(members) >= min_live:
                return gen, members
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    "elastic rendezvous timed out after %.0fs: %d of "
                    "%d workers joined (%r)"
                    % (self.rendezvous_timeout_sec, len(members),
                       min_live, members))
            # a quorum wait can outlast the master's heartbeat
            # deadline: beat so the wait reads as alive, not dead (the
            # master refreshes liveness even on a GENMISMATCH beat;
            # the members() recheck above adopts any new generation)
            try:
                self._client.heartbeat(self.worker_id, gen)
            except (GenerationMismatch, ConnectionError, OSError):
                pass
            time.sleep(0.1)

    def _bring_up(self):
        gen, members = self._rendezvous()
        _GENERATION.set(gen)
        _observe_deaths(self._client)
        self.generations.append(gen)
        # ranks follow sorted worker_id order in the settled member
        # list — dense, consistent across workers at this generation
        rank, world_n = members.index(self.worker_id), len(members)
        if self.coordinator_address:
            # re-init at the SURVIVING world size: jax requires rank 0
            # on the coordinator host, so in coordinator mode name the
            # coordinator host's worker to sort first (e.g. "w0") —
            # and note that losing that host, like losing the master,
            # is not survivable
            pid, nproc = init_multihost(
                self.coordinator_address,
                num_processes=world_n, process_id=rank,
                initialization_timeout_sec=(
                    self.initialization_timeout_sec))
        else:
            pid, nproc = rank, world_n
        world = ElasticWorld(gen, world_n, self.worker_id,
                             self._client, process_id=pid,
                             num_processes=nproc)
        _log.structured("elastic_bring_up", generation=gen,
                        live=world_n, rank=rank,
                        worker=self.worker_id,
                        restarts=self.restarts)
        return world

    def _escalate(self, trainer):
        """Wire the hang-escalation chain into the trainer's policy (if
        it has one): watchdog overrun -> collective_abort -> abort.
        The wrapper also marks the abort as watchdog-originated so the
        loop's KeyboardInterrupt handler restarts on a hang but lets a
        real user Ctrl-C propagate."""
        policy = getattr(trainer, "policy", None)
        if policy is None or not policy.step_deadline_sec:
            return
        inner = getattr(policy, "on_hang", None)

        def on_hang(step, elapsed):
            self._hang_abort = True
            if inner is not None:
                inner(step, elapsed)
            else:
                collective_abort("hung step %s (%.1fs)"
                                 % (step, elapsed))
        policy.on_hang = on_hang
        # without the abort the escalation can't unwind a wedged loop
        policy.watchdog_abort = True

    # -- the loop ---------------------------------------------------------
    def run(self, num_passes=1, event_handler=None, prefetch=0,
            staging=False):
        """Train to completion across restarts; returns the final
        ``train`` result. Raises :class:`ElasticRestartLimit` after
        ``max_restarts`` teardown/rebuild cycles.

        ``prefetch``/``staging`` default OFF here (unlike
        ``Trainer.train``, which defaults to a staged prefetch of 8):
        a hang-abort must unwind through ``collective_abort`` while the
        staging thread may itself be blocked in a ``device_put`` on the
        dead runtime, so the conservative default keeps the abort path
        free of background device work. Pass ``prefetch=8,
        staging=True`` explicitly to restore the PR-4 staged pipeline
        when throughput matters more than worst-case abort latency."""
        trigger_t = None  # set at restart detection, for resume latency
        while True:
            restart_reason = None
            result = None
            hb = None
            try:
                world = self._bring_up()
                # beats start BEFORE the (possibly slow) build: a
                # worker mid-rebuild (init_multihost, mesh, first
                # compile) is alive, not dead — without a beat covering
                # this window the master would reap it at the heartbeat
                # deadline and fence every healthy survivor into yet
                # another restart. A generation change landing before
                # the trainer exists is parked and delivered right
                # after build; the lock makes park-vs-publish atomic,
                # so a change can never fall between the heartbeat
                # thread's box check and the main thread's park check.
                park = threading.Lock()
                trainer_box, pending_restart = [], []

                def _on_change(old, new, live):
                    reason = "generation_%d_to_%d" % (old, new)
                    with park:
                        if trainer_box:
                            trainer_box[0].request_restart(reason)
                        else:
                            pending_restart.append(reason)

                hb = MembershipHeartbeat(
                    self.master_port, self.worker_id, world.generation,
                    host=self.master_host,
                    interval_sec=self.heartbeat_interval_sec,
                    on_change=_on_change)
                hb.start()
                self._hang_abort = False
                try:
                    trainer, reader = self.build(world)
                    self._escalate(trainer)
                    with park:
                        trainer_box.append(trainer)
                        parked = (pending_restart[0]
                                  if pending_restart else None)
                    if parked is not None:
                        trainer.request_restart(parked)
                    trainer.startup()  # restore newest intact ckpt
                    if trigger_t is not None:
                        resume_s = time.perf_counter() - trigger_t
                        _RESUME_SECONDS.observe(resume_s)
                        _log.structured(
                            "elastic_resumed",
                            generation=world.generation,
                            step=trainer.step_id,
                            resume_seconds=round(resume_s, 3))
                        trigger_t = None
                    result = trainer.train(reader,
                                           num_passes=num_passes,
                                           event_handler=event_handler,
                                           prefetch=prefetch,
                                           staging=staging)
                except GenerationMismatch as e:
                    restart_reason = ("generation_fence_%d"
                                      % e.current_generation)
                finally:
                    hb.stop()
            except KeyboardInterrupt:
                # watchdog abort: the wedged step was escalated through
                # on_hang (collective_abort already ran) and the
                # interrupt unwound the loop — restart, don't die. The
                # interrupt can land anywhere in the iteration, not
                # just inside train(): interrupt_main delivers
                # asynchronously, so a step that was slow-but-alive can
                # finish and leave the interrupt to arrive during
                # startup, the finally's hb.stop(), or the next
                # bring-up — catching at iteration scope keeps every
                # landing site on the restart path. A KeyboardInterrupt
                # with no preceding escalation is a real user Ctrl-C:
                # propagate it.
                if not self._hang_abort:
                    raise
                self._hang_abort = False
                restart_reason = "collective_hang_abort"
                if hb is not None:
                    hb.stop()  # idempotent; re-run if interrupted
            if restart_reason is None:
                if result and result.get("restart"):
                    restart_reason = result.get("reason", "requested")
                else:
                    return result
            trigger_t = time.perf_counter()
            self.restarts += 1
            _RESTARTS.inc()
            _log.structured("elastic_restart", reason=restart_reason,
                            restarts=self.restarts,
                            max_restarts=self.max_restarts)
            if self.restarts > self.max_restarts:
                raise ElasticRestartLimit(
                    "elastic restart budget exhausted: %d restarts "
                    "(last reason: %s)" % (self.restarts,
                                           restart_reason))
            collective_abort(restart_reason)
