"""Client + launcher for the elastic task master (native/task_master.cc).

go/master parity (SURVEY §5.3): GetTask/TaskFinished/TaskFailed RPCs with
task epochs, timeout requeue, failure budget, and disk-snapshot recovery.
The reference's cgo master client (python/paddle/v2/master/client.py) maps
to MasterClient; cloud_reader maps to ElasticDataDispatcher.reader().
"""

import os
import random
import socket
import subprocess
import time

from .. import native

__all__ = ["MasterServer", "MasterClient", "ElasticDataDispatcher",
           "GenerationMismatch"]


class GenerationMismatch(RuntimeError):
    """A call carried a stale cluster generation (the caller belongs to
    a membership epoch that a worker death has since superseded). The
    current generation rides along so the caller can re-register."""

    def __init__(self, current_generation, message=None):
        super().__init__(message or
                         "stale cluster generation (current is %d)"
                         % current_generation)
        self.current_generation = current_generation


class MasterServer:
    """Spawns the C++ task_master daemon on localhost.

    ``heartbeat_timeout_ms`` is the membership deadline: a worker that
    REGistered and then misses heartbeats for this long is declared
    dead (generation bump + immediate re-lease of its chunks). Workers
    that never register — every pre-elastic client — are unaffected.
    """

    def __init__(self, snapshot_path, port=0, timeout_sec=30,
                 failure_max=3, heartbeat_timeout_ms=10000):
        binary = native.task_master_binary()
        self.proc = subprocess.Popen(
            [binary, str(port), snapshot_path, str(timeout_sec),
             str(failure_max), str(int(heartbeat_timeout_ms))],
            stdout=subprocess.PIPE, text=True)
        line = self.proc.stdout.readline().strip()
        if not line.startswith("LISTENING"):
            raise RuntimeError("task_master failed to start: %r" % line)
        self.port = int(line.split()[1])
        self.snapshot_path = snapshot_path

    def stop(self, graceful=True):
        """Stop the daemon. ``graceful`` sends SHUTDOWN and waits: the
        master answers every client line already on the wire (including
        lines queued behind the SHUTDOWN itself) before its connection
        threads close — in-flight work drains instead of dying with a
        reset socket."""
        if self.proc.poll() is not None:
            return
        if graceful:
            try:
                MasterClient(self.port).shutdown()
                self.proc.wait(timeout=5)
                return
            except Exception:
                pass
        self.proc.kill()
        self.proc.wait()

    def kill(self):
        """Hard-kill (for failover tests)."""
        self.proc.kill()
        self.proc.wait()


class MasterClient:
    """One line-protocol connection. NOT thread-safe — give each thread
    (e.g. a heartbeat thread) its own client."""

    def __init__(self, port, host="127.0.0.1", retries=3,
                 backoff=0.1, backoff_cap=2.0):
        self.addr = (host, port)
        self.retries = retries
        self.backoff = backoff
        self.backoff_cap = backoff_cap
        self._sock = None
        self._file = None

    def _connect(self):
        s = socket.create_connection(self.addr, timeout=10)
        self._file = s.makefile("r")
        self._sock = s

    def _close(self):
        """Release the socket AND its makefile wrapper — dropping the
        references without close() leaks both fds on every
        reconnect/failure until GC happens to run."""
        for f in (self._file, self._sock):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
        self._file = None
        self._sock = None

    def _retry_delay(self, attempt):
        """Jittered exponential backoff: the old fixed-ramp retry made
        every disconnected worker hammer a restarting master in
        lockstep; the jitter (uniform over [d/2, d]) decorrelates the
        reconnect herd."""
        d = min(self.backoff_cap, self.backoff * (2 ** attempt))
        return d * (0.5 + 0.5 * random.random())

    def _call(self, line):
        for attempt in range(self.retries):
            try:
                if self._sock is None:
                    self._connect()
                self._sock.sendall((line + "\n").encode())
                resp = self._file.readline()
                if resp:
                    return resp.strip()
            except OSError:
                pass
            self._close()
            time.sleep(self._retry_delay(attempt))
        raise ConnectionError("master unreachable at %s:%d" % self.addr)

    @staticmethod
    def _fence_check(resp):
        if resp.startswith("GENMISMATCH"):
            raise GenerationMismatch(int(resp.split()[1]))
        return resp

    @staticmethod
    def _gen_suffix(generation):
        return "" if generation is None else " %d" % generation

    def ping(self):
        return self._call("PING") == "PONG"

    def add_task(self, task_id, payload=""):
        return self._call("ADD %s %s" % (task_id, payload))

    def get_task(self, worker_id="w0", generation=None):
        """Returns (task_id, epoch, payload) or None (retry later) or
        'ALLDONE'. With ``generation``, the call is fenced: a stale
        generation raises GenerationMismatch instead of leasing."""
        resp = self._fence_check(self._call(
            "GET %s%s" % (worker_id, self._gen_suffix(generation))))
        if resp == "NONE":
            return None
        if resp == "ALLDONE":
            return "ALLDONE"
        parts = resp.split(" ", 3)
        return (parts[1], int(parts[2]),
                parts[3] if len(parts) > 3 else "")

    def task_finished(self, task_id, epoch, generation=None):
        return self._fence_check(self._call(
            "FIN %s %d%s" % (task_id, epoch,
                             self._gen_suffix(generation))))

    def task_failed(self, task_id, epoch, generation=None):
        return self._fence_check(self._call(
            "FAIL %s %d%s" % (task_id, epoch,
                              self._gen_suffix(generation))))

    # -- cluster membership (elastic multi-host) --------------------------
    def register(self, worker_id):
        """(Re-)register as a live member at the current generation.
        Returns (generation, n_live)."""
        resp = self._call("REG %s" % worker_id)
        if not resp.startswith("GEN "):
            raise ConnectionError("bad REG response %r" % resp)
        _, gen, n_live = resp.split()
        return int(gen), int(n_live)

    def heartbeat(self, worker_id, generation):
        """One liveness beat. Returns the current generation on match;
        raises GenerationMismatch when the cluster resized (or a
        restarted master forgot us) — re-register and rebuild."""
        resp = self._call("HB %s %d" % (worker_id, generation))
        self._fence_check(resp)
        return int(resp.split()[1])

    def cluster(self):
        """{'generation', 'live', 'deaths'} — the membership view."""
        parts = self._call("CLUSTER").split()
        return {"generation": int(parts[1]), "live": int(parts[2]),
                "deaths": int(parts[3])}

    def members(self):
        """(generation, sorted live worker ids) in ONE consistent
        snapshot: any membership change after it bumps the generation,
        so a stale view is always fenced rather than silently wrong.
        Rank = index in the sorted list."""
        parts = self._call("MEMBERS").split()
        n = int(parts[2])
        return int(parts[1]), parts[3:3 + n]

    def reset_pass(self):
        return self._call("RESET")

    def stats(self):
        parts = self._call("STATS").split()
        return {"todo": int(parts[1]), "pending": int(parts[2]),
                "done": int(parts[3]), "failed": int(parts[4])}

    def shutdown(self):
        return self._call("SHUTDOWN")


class ElasticDataDispatcher:
    """Dataset-as-task-queue: RecordIO chunks dispatched through the
    master; a worker's reader pulls chunk leases and yields samples
    (reference cloud_reader + master GetTask loop)."""

    def __init__(self, client, recordio_path, worker_id="w0",
                 generation=None):
        """``recordio_path``: one path, a glob pattern, or a list of
        paths (the output of ``dataset.common.convert`` — reference
        cloud_reader's etcd glob, go/master/service.go partition).

        ``generation``: fence every lease call with this cluster
        generation (elastic runtime): after a resize, a dispatcher
        built for the old generation raises GenerationMismatch instead
        of silently corrupting the lease table."""
        self.client = client
        self.generation = generation
        if isinstance(recordio_path, (list, tuple)):
            self.paths = list(recordio_path)
        elif any(ch in recordio_path for ch in "*?["):
            import glob
            self.paths = sorted(glob.glob(recordio_path))
        else:
            self.paths = [recordio_path]
        if not self.paths:
            raise ValueError("no recordio files match %r" % recordio_path)
        self.worker_id = worker_id

    def register_dataset(self):
        from ..reader import recordio as rio
        total = 0
        for pi, path in enumerate(self.paths):
            n = rio.num_chunks(path)
            for i in range(n):
                self.client.add_task("chunk-%d-%d" % (pi, i),
                                     "%d:%d" % (pi, i))
            total += n
        return total

    def reader(self, poll_interval=0.2, deserialize=None):
        """Yield samples from leased chunks until the pass completes.
        Chunk completion is reported per-lease; a crash mid-chunk means
        the chunk is re-dispatched after the timeout — at-least-once, as
        in the reference."""
        from ..reader import recordio as rio
        import pickle
        de = deserialize or pickle.loads

        from ..resilience import faults as _faults

        def gen():
            leases = 0
            while True:
                # chaos hook: "kill master mid-pass" — arm with a
                # callback that kills (and restarts) the MasterServer;
                # the client's retry loop + the master's disk snapshot
                # carry the pass across the outage
                _faults.fire_point("master_kill", leases)
                leases += 1
                task = self.client.get_task(self.worker_id,
                                            generation=self.generation)
                if task == "ALLDONE":
                    return
                if task is None:
                    time.sleep(poll_interval)
                    continue
                task_id, epoch, payload = task
                if ":" in payload:
                    pi, chunk = (int(v) for v in payload.split(":"))
                else:  # single-file payloads from older snapshots
                    pi, chunk = 0, int(payload)
                try:
                    for sample in rio.chunked_reader(
                            self.paths[pi], [chunk], deserialize=de)():
                        yield sample
                except Exception:
                    self.client.task_failed(task_id, epoch,
                                            generation=self.generation)
                    continue
                self.client.task_finished(task_id, epoch,
                                          generation=self.generation)
        return gen
