"""Client + launcher for the elastic task master (native/task_master.cc).

go/master parity (SURVEY §5.3): GetTask/TaskFinished/TaskFailed RPCs with
task epochs, timeout requeue, failure budget, and disk-snapshot recovery.
The reference's cgo master client (python/paddle/v2/master/client.py) maps
to MasterClient; cloud_reader maps to ElasticDataDispatcher.reader().
"""

import os
import socket
import subprocess
import time

from .. import native

__all__ = ["MasterServer", "MasterClient", "ElasticDataDispatcher"]


class MasterServer:
    """Spawns the C++ task_master daemon on localhost."""

    def __init__(self, snapshot_path, port=0, timeout_sec=30,
                 failure_max=3):
        binary = native.task_master_binary()
        self.proc = subprocess.Popen(
            [binary, str(port), snapshot_path, str(timeout_sec),
             str(failure_max)],
            stdout=subprocess.PIPE, text=True)
        line = self.proc.stdout.readline().strip()
        if not line.startswith("LISTENING"):
            raise RuntimeError("task_master failed to start: %r" % line)
        self.port = int(line.split()[1])
        self.snapshot_path = snapshot_path

    def stop(self, graceful=True):
        if self.proc.poll() is not None:
            return
        if graceful:
            try:
                MasterClient(self.port).shutdown()
                self.proc.wait(timeout=5)
                return
            except Exception:
                pass
        self.proc.kill()
        self.proc.wait()

    def kill(self):
        """Hard-kill (for failover tests)."""
        self.proc.kill()
        self.proc.wait()


class MasterClient:
    def __init__(self, port, host="127.0.0.1", retries=3):
        self.addr = (host, port)
        self.retries = retries
        self._sock = None
        self._file = None

    def _connect(self):
        s = socket.create_connection(self.addr, timeout=10)
        self._file = s.makefile("r")
        self._sock = s

    def _close(self):
        """Release the socket AND its makefile wrapper — dropping the
        references without close() leaks both fds on every
        reconnect/failure until GC happens to run."""
        for f in (self._file, self._sock):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
        self._file = None
        self._sock = None

    def _call(self, line):
        for attempt in range(self.retries):
            try:
                if self._sock is None:
                    self._connect()
                self._sock.sendall((line + "\n").encode())
                resp = self._file.readline()
                if resp:
                    return resp.strip()
            except OSError:
                pass
            self._close()
            time.sleep(0.2 * (attempt + 1))
        raise ConnectionError("master unreachable at %s:%d" % self.addr)

    def ping(self):
        return self._call("PING") == "PONG"

    def add_task(self, task_id, payload=""):
        return self._call("ADD %s %s" % (task_id, payload))

    def get_task(self, worker_id="w0"):
        """Returns (task_id, epoch, payload) or None (retry later) or
        'ALLDONE'."""
        resp = self._call("GET %s" % worker_id)
        if resp == "NONE":
            return None
        if resp == "ALLDONE":
            return "ALLDONE"
        parts = resp.split(" ", 3)
        return (parts[1], int(parts[2]),
                parts[3] if len(parts) > 3 else "")

    def task_finished(self, task_id, epoch):
        return self._call("FIN %s %d" % (task_id, epoch))

    def task_failed(self, task_id, epoch):
        return self._call("FAIL %s %d" % (task_id, epoch))

    def reset_pass(self):
        return self._call("RESET")

    def stats(self):
        parts = self._call("STATS").split()
        return {"todo": int(parts[1]), "pending": int(parts[2]),
                "done": int(parts[3]), "failed": int(parts[4])}

    def shutdown(self):
        return self._call("SHUTDOWN")


class ElasticDataDispatcher:
    """Dataset-as-task-queue: RecordIO chunks dispatched through the
    master; a worker's reader pulls chunk leases and yields samples
    (reference cloud_reader + master GetTask loop)."""

    def __init__(self, client, recordio_path, worker_id="w0"):
        """``recordio_path``: one path, a glob pattern, or a list of
        paths (the output of ``dataset.common.convert`` — reference
        cloud_reader's etcd glob, go/master/service.go partition)."""
        self.client = client
        if isinstance(recordio_path, (list, tuple)):
            self.paths = list(recordio_path)
        elif any(ch in recordio_path for ch in "*?["):
            import glob
            self.paths = sorted(glob.glob(recordio_path))
        else:
            self.paths = [recordio_path]
        if not self.paths:
            raise ValueError("no recordio files match %r" % recordio_path)
        self.worker_id = worker_id

    def register_dataset(self):
        from ..reader import recordio as rio
        total = 0
        for pi, path in enumerate(self.paths):
            n = rio.num_chunks(path)
            for i in range(n):
                self.client.add_task("chunk-%d-%d" % (pi, i),
                                     "%d:%d" % (pi, i))
            total += n
        return total

    def reader(self, poll_interval=0.2, deserialize=None):
        """Yield samples from leased chunks until the pass completes.
        Chunk completion is reported per-lease; a crash mid-chunk means
        the chunk is re-dispatched after the timeout — at-least-once, as
        in the reference."""
        from ..reader import recordio as rio
        import pickle
        de = deserialize or pickle.loads

        from ..resilience import faults as _faults

        def gen():
            leases = 0
            while True:
                # chaos hook: "kill master mid-pass" — arm with a
                # callback that kills (and restarts) the MasterServer;
                # the client's retry loop + the master's disk snapshot
                # carry the pass across the outage
                _faults.fire_point("master_kill", leases)
                leases += 1
                task = self.client.get_task(self.worker_id)
                if task == "ALLDONE":
                    return
                if task is None:
                    time.sleep(poll_interval)
                    continue
                task_id, epoch, payload = task
                if ":" in payload:
                    pi, chunk = (int(v) for v in payload.split(":"))
                else:  # single-file payloads from older snapshots
                    pi, chunk = 0, int(payload)
                try:
                    for sample in rio.chunked_reader(
                            self.paths[pi], [chunk], deserialize=de)():
                        yield sample
                except Exception:
                    self.client.task_failed(task_id, epoch)
                    continue
                self.client.task_finished(task_id, epoch)
        return gen
