"""v2 layer DSL (reference ``python/paddle/v2/layer.py`` +
``trainer_config_helpers/layers.py`` ~85 funcs): the keyword-argument
graph-builder surface of the legacy API, lowered onto the fluid-style
layers. Sequence-typed data layers produce a padded (data, length) pair
under the hood (the LoD replacement, SURVEY §5.7); every v2 layer that
consumed LoD consults the hidden length var.
"""

from .. import layers as _L
from .. import nets as _nets
from . import data_type as _dt

__all__ = ["data", "fc", "embedding", "pooling", "concat",
           "classification_cost", "regression_cost", "mse_cost",
           "cross_entropy_cost", "lstmemory_group", "gru_group",
           "max_id", "dropout", "img_conv", "img_pool", "batch_norm"]

def _input_types(program=None):
    """var name -> (InputType, length var) feeding table, scoped to the
    owning program (a module-level global keyed by user-chosen names
    would leak stale entries across topologies that reuse a name, e.g.
    two models both calling their input 'pixel')."""
    from ..core.framework import default_main_program
    prog = program or default_main_program()
    table = getattr(prog, "_v2_input_types", None)
    if table is None:
        table = prog._v2_input_types = {}
    return table


def _length_of(var):
    entry = _input_types().get(
        getattr(var, "_v2_source", None) or var.name)
    return entry[1] if entry else getattr(var, "_v2_length", None)


def _tag(out, src):
    """Propagate the sequence-length var through unary layers."""
    ln = _length_of(src)
    if ln is not None:
        out._v2_length = ln
    return out


def data(name, type, **kwargs):
    """v2 data layer: shape/dtype/sequence-ness from the InputType."""
    if type.is_seq:
        var = _L.data(name, shape=[None], dtype=type.dtype, **kwargs)
        length = _L.data(name + "@len", shape=[], dtype="int64",
                         **kwargs)
        var._v2_length = length
        _input_types()[var.name] = (type, length)
        return var
    shape = [type.dim] if type.dtype == "float32" else [1]
    var = _L.data(name, shape=shape, dtype=type.dtype, **kwargs)
    _input_types()[var.name] = (type, None)
    return var


def _act_name(act):
    return getattr(act, "name", act) if act is not None else None


def fc(input, size, act=None, param_attr=None, bias_attr=None, **kwargs):
    inputs = input if isinstance(input, (list, tuple)) else [input]
    ndim = max(len(v.shape or ()) for v in inputs)
    out = _L.fc(input, size, act=_act_name(act), param_attr=param_attr,
                bias_attr=bias_attr,
                num_flatten_dims=2 if ndim >= 3 else 1, **kwargs)
    if _act_name(act) == "softmax":
        out._v2_softmaxed = True  # classification_cost picks plain CE
    return _tag(out, inputs[0])


def embedding(input, size, param_attr=None, **kwargs):
    entry = _input_types().get(input.name)
    vocab = entry[0].dim if entry else None
    if vocab is None:
        raise ValueError("embedding needs a data layer with "
                         "integer_value[_sequence] type")
    out = _L.embedding(input, size=[vocab, size], param_attr=param_attr,
                       **kwargs)
    return _tag(out, input)


def pooling(input, pooling_type=None, **kwargs):
    """Sequence pooling over the time axis (v2 pooling layer)."""
    ptype = getattr(pooling_type, "name", None) or "max"
    return _L.sequence_pool(input, ptype, length=_length_of(input),
                            **kwargs)


def concat(input, **kwargs):
    return _L.concat(list(input), axis=-1, **kwargs)


def dropout(input, dropout_rate=0.5, **kwargs):
    return _tag(_L.dropout(input, dropout_prob=dropout_rate, **kwargs),
                input)


def classification_cost(input, label, **kwargs):
    """softmax_with_cross_entropy mean (v2 classification_cost: the
    input is pre-softmax unless already activated; reference applies
    softmax inside the cost when the layer's act is Softmax — here the
    convention is: pass logits OR softmax output, cross_entropy picks
    the right path by checking the producing layer)."""
    if getattr(input, "_v2_softmaxed", False):
        return _L.mean(_L.cross_entropy(input, label, **kwargs))
    return _L.mean(_L.softmax_with_cross_entropy(input, label, **kwargs))


def cross_entropy_cost(input, label, **kwargs):
    return _L.mean(_L.cross_entropy(input, label, **kwargs))


def regression_cost(input, label, **kwargs):
    return _L.mean(_L.square_error_cost(input, label, **kwargs))


mse_cost = regression_cost


def lstmemory_group(input, size, reverse=False, **kwargs):
    """v2 simple_lstm-style group over a sequence input."""
    out = _nets.simple_lstm(input, size, length=_length_of(input),
                            is_reverse=reverse, **kwargs)
    return _tag(out, input)


def gru_group(input, size, reverse=False, **kwargs):
    out = _nets.simple_gru(input, size, length=_length_of(input),
                           is_reverse=reverse, **kwargs)
    return _tag(out, input)


def max_id(input, **kwargs):
    out, idx = _L.topk(input, k=1, **kwargs)
    return idx


def img_conv(input, filter_size, num_filters, act=None, padding=0,
             stride=1, **kwargs):
    return _L.conv2d(input, num_filters=num_filters,
                     filter_size=filter_size, padding=padding,
                     stride=stride, act=_act_name(act), **kwargs)


def img_pool(input, pool_size, pool_type=None, stride=1, **kwargs):
    ptype = getattr(pool_type, "name", None) or "max"
    if ptype == "average":
        ptype = "avg"
    return _L.pool2d(input, pool_size=pool_size, pool_type=ptype,
                     pool_stride=stride, **kwargs)


def batch_norm(input, act=None, **kwargs):
    return _L.batch_norm(input, act=_act_name(act), **kwargs)


def parse_network(*outputs):
    """v2 topology hook — programs ARE the topology here."""
    return list(outputs)
