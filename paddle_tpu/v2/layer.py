"""v2 layer DSL — the full ~85-function keyword-argument surface of
``python/paddle/trainer_config_helpers/layers.py`` (SURVEY A.5) plus
the projection/operator family for mixed_layer, lowered onto the
fluid-style layers. Sequence-typed data layers produce a padded
(data, length) pair under the hood (the LoD replacement, SURVEY §5.7);
every v2 layer that consumed LoD consults the hidden length var.

Naming: the reference exports both ``fc_layer``-style names and bare
``fc`` (via ``paddle.v2.layer``'s __convert_to_v2__); this module uses
the bare names and aliases the ``*_layer`` spellings.
"""

import numpy as np

from .. import layers as _L
from .. import nets as _nets
from ..param_attr import ParamAttr
from . import data_type as _dt

# var name -> (InputType, length var or None); scoped per program


def _input_types(program=None):
    """var name -> (InputType, length var) feeding table, scoped to the
    owning program (a module-level global keyed by user-chosen names
    would leak stale entries across topologies that reuse a name, e.g.
    two models both calling their input 'pixel')."""
    from ..core.framework import default_main_program
    prog = program or default_main_program()
    table = getattr(prog, "_v2_input_types", None)
    if table is None:
        table = prog._v2_input_types = {}
    return table


def _length_of(var):
    entry = _input_types().get(
        getattr(var, "_v2_source", None) or var.name)
    return entry[1] if entry else getattr(var, "_v2_length", None)


def _tag(out, src):
    """Propagate the sequence-length (and sub-length) vars through
    unary layers."""
    ln = _length_of(src)
    if ln is not None:
        out._v2_length = ln
    sl = getattr(src, "_v2_sublen", None)
    if sl is not None:
        out._v2_sublen = sl
    return out


def _act_name(act):
    return getattr(act, "name", act) if act is not None else None


def _first(input):
    return input[0] if isinstance(input, (list, tuple)) else input


# ---- data / io -------------------------------------------------------

def data(name, type, **kwargs):
    """v2 data layer: shape/dtype/sequence-ness from the InputType.

    Realizations (data_type.py, SURVEY §5.7 static shapes):
    * sequence -> padded ids/values + ``name@len`` length var;
    * sub-sequence (seq_type=2) -> [B, S, T, ...] + ``name@len`` [B] +
      ``name@sublen`` [B, S] (ops/nested_ops.py convention);
    * sparse pair types (sparse_float_vector*, sparse_binary_vector
      at sequence levels) -> ragged-K ids + ``name@value`` weights
      (all-ones for binary rows), one extra trailing K axis below the
      sequence levels — reference SparseFloat/SparseBinaryScanner
      (py_paddle/dataprovider_converter.py:154,184)."""
    if getattr(type, "is_sparse_pair", False):
        ndim = type.seq_type + 1  # K, plus one axis per seq level
        var = _L.data(name, shape=[None] * ndim, dtype="int64",
                      **kwargs)
        values = _L.data(name + "@value", shape=[None] * ndim,
                         dtype="float32", **kwargs)
        var._v2_value = values
        length = None
        if type.seq_type >= 1:
            length = _L.data(name + "@len", shape=[], dtype="int64",
                             **kwargs)
            var._v2_length = length
        if type.seq_type == 2:
            sublen = _L.data(name + "@sublen", shape=[None],
                             dtype="int64", **kwargs)
            var._v2_sublen = sublen
        _input_types()[var.name] = (type, length)
        return var
    if getattr(type, "is_nested", False):
        var = _L.data(name, shape=[None, None], dtype=type.dtype,
                      **kwargs)
        length = _L.data(name + "@len", shape=[], dtype="int64",
                         **kwargs)
        sublen = _L.data(name + "@sublen", shape=[None], dtype="int64",
                         **kwargs)
        var._v2_length = length
        var._v2_sublen = sublen
        _input_types()[var.name] = (type, length)
        return var
    if type.is_seq:
        var = _L.data(name, shape=[None], dtype=type.dtype, **kwargs)
        length = _L.data(name + "@len", shape=[], dtype="int64",
                         **kwargs)
        var._v2_length = length
        _input_types()[var.name] = (type, length)
        return var
    shape = [type.dim] if type.dtype == "float32" else [1]
    var = _L.data(name, shape=shape, dtype=type.dtype, **kwargs)
    _input_types()[var.name] = (type, None)
    return var


def printer(input, format=None, **kwargs):
    """Print layer (reference printer_layer / Print op)."""
    return _L.Print(_first(input), message=format or "")


# ---- core nn ---------------------------------------------------------

def _sparse_float_rowsum(input, width, param_attr=None):
    """sum_k values_k * Table[ids_k] — the sparse-row × dense-matrix
    product of the reference's sparse_float_vector path
    (``math/CpuSparseMatrix.h:24``, fc over sparse input) computed by
    gather + weighted sum; the dense [B, dim] row never materializes."""
    entry = _input_types().get(input.name)
    if entry is None:
        raise ValueError("sparse-float input %r has no registered "
                         "InputType" % input.name)
    vocab = entry[0].dim
    rows = _L.embedding(input, size=[vocab, width],
                        param_attr=param_attr,
                        keep_dims=True)               # [..., K, width]
    weighted = _L.elementwise_mul(rows, input._v2_value, axis=0)
    # sum over K (the ragged sparse-row axis); 0-padded values make
    # padding rows no-ops, so no mask is needed
    return _tag(_L.reduce_sum(weighted, dim=-2), input)


def fc(input, size, act=None, param_attr=None, bias_attr=None, **kwargs):
    inputs = input if isinstance(input, (list, tuple)) else [input]
    if any(getattr(v, "_v2_value", None) is not None for v in inputs):
        parts = []
        for v in inputs:
            if getattr(v, "_v2_value", None) is not None:
                parts.append(_sparse_float_rowsum(v, size, param_attr))
            else:
                parts.append(_L.fc(
                    v, size, bias_attr=False, param_attr=param_attr,
                    num_flatten_dims=2 if len(v.shape or ()) >= 3
                    else 1, **kwargs))
        out = parts[0] if len(parts) == 1 else _L.sums(parts)
        if bias_attr is not False:
            from ..layer_helper import LayerHelper
            helper = LayerHelper("fc_sparse_bias")
            b = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                        shape=[size], dtype=out.dtype,
                                        is_bias=True)
            out = _L.elementwise_add(out, b)
        act_n = _act_name(act)
        out = getattr(_L, act_n)(out) if act_n else out
        if act_n == "softmax":
            out._v2_softmaxed = True
        # bias/act wrap fresh Variables — re-tag sequence lengths so
        # downstream pooling masks padding (first tagged input wins)
        for v in inputs:
            if _length_of(v) is not None:
                return _tag(out, v)
        return out
    ndim = max(len(v.shape or ()) for v in inputs)
    out = _L.fc(input, size, act=_act_name(act), param_attr=param_attr,
                bias_attr=bias_attr,
                num_flatten_dims=2 if ndim >= 3 else 1, **kwargs)
    if _act_name(act) == "softmax":
        out._v2_softmaxed = True  # classification_cost picks plain CE
    return _tag(out, inputs[0])


def embedding(input, size, param_attr=None, **kwargs):
    entry = _input_types().get(input.name)
    vocab = entry[0].dim if entry else None
    if vocab is None:
        raise ValueError("embedding needs a data layer with "
                         "integer_value[_sequence] type")
    out = _L.embedding(input, size=[vocab, size], param_attr=param_attr,
                       **kwargs)
    return _tag(out, input)


def selective_fc(input, size, select=None, act=None, param_attr=None,
                 bias_attr=None, **kwargs):
    return _tag(_L.selective_fc(_first(input), size, select=select,
                                act=_act_name(act),
                                param_attr=param_attr,
                                bias_attr=bias_attr, **kwargs),
                _first(input))


def tensor(a, b, size, act=None, param_attr=None, bias_attr=None,
           **kwargs):
    """tensor_layer: y = a^T W b (bilinear)."""
    out = _L.bilinear_tensor_product(a, b, size,
                                     param_attr=param_attr,
                                     bias_attr=bias_attr, **kwargs)
    act_n = _act_name(act)
    return getattr(_L, act_n)(out) if act_n else out


def data_norm(input, mode="z-score", stats=None, **kwargs):
    return _L.data_norm(input, mode=mode, stats=stats, **kwargs)


# ---- conv / pool / norm family --------------------------------------

def img_conv(input, filter_size, num_filters, num_channels=None,
             act=None, padding=0, stride=1, groups=1, param_attr=None,
             bias_attr=None, **kwargs):
    return _L.conv2d(input, num_filters=num_filters,
                     filter_size=filter_size, padding=padding,
                     stride=stride, groups=groups,
                     act=_act_name(act), param_attr=param_attr,
                     bias_attr=bias_attr, **kwargs)


def img_conv3d(input, filter_size, num_filters, act=None, padding=0,
               stride=1, **kwargs):
    out = _L.conv3d(input, num_filters=num_filters,
                    filter_size=filter_size, padding=padding,
                    stride=stride, **kwargs)
    act_n = _act_name(act)
    return getattr(_L, act_n)(out) if act_n else out


def img_pool(input, pool_size, pool_type=None, stride=1, padding=0,
             **kwargs):
    ptype = getattr(pool_type, "name", None) or "max"
    if ptype in ("average", "avg"):
        ptype = "avg"
    return _L.pool2d(input, pool_size=pool_size, pool_type=ptype,
                     pool_stride=stride, pool_padding=padding, **kwargs)


def img_pool3d(input, pool_size, pool_type=None, stride=1, padding=0,
               **kwargs):
    ptype = getattr(pool_type, "name", None) or "max"
    if ptype in ("average", "avg"):
        ptype = "avg"
    return _L.pool3d(input, pool_size=pool_size, pool_type=ptype,
                     pool_stride=stride, pool_padding=padding, **kwargs)


def img_cmrnorm(input, size=5, scale=0.0001, power=0.75, **kwargs):
    """Cross-map response norm = LRN (reference img_cmrnorm_layer)."""
    return _L.lrn(input, n=size, alpha=scale, beta=power, **kwargs)


def batch_norm(input, act=None, is_test=False, **kwargs):
    return _L.batch_norm(input, act=_act_name(act), is_test=is_test,
                         **kwargs)


def spp(input, pyramid_height=3, pool_type=None, **kwargs):
    ptype = getattr(pool_type, "name", None) or "max"
    return _L.spp(input, pyramid_height=pyramid_height,
                  pool_type=ptype, **kwargs)


def maxout(input, groups, **kwargs):
    return _L.maxout(input, groups=groups, **kwargs)


def pad(input, pad_c=None, pad_h=None, pad_w=None, **kwargs):
    """Pad NCHW maps per dim ([before, after] each; reference
    pad_layer)."""
    c, h, w = (pad_c or [0, 0]), (pad_h or [0, 0]), (pad_w or [0, 0])
    return _L.pad(input, paddings=[0, 0, c[0], c[1], h[0], h[1],
                                   w[0], w[1]], **kwargs)


def crop(input, offset, shape, **kwargs):
    return _L.crop(input, offsets=offset, shape=shape, **kwargs)


def block_expand(input, block_x, block_y, stride_x=None, stride_y=None,
                 padding_x=0, padding_y=0, **kwargs):
    """im2sequence (reference BlockExpandLayer); padding applied as an
    explicit pad of the maps first."""
    x = input
    if padding_x or padding_y:
        x = _L.pad(x, paddings=[0, 0, 0, 0, padding_y, padding_y,
                                padding_x, padding_x])
    return _L.im2sequence(
        x, filter_size=[block_y, block_x],
        stride=[stride_y or block_y, stride_x or block_x], **kwargs)


def rotate(input, height, width, **kwargs):
    return _L.rotate(input, height=height, width=width, **kwargs)


def resize(input, size, **kwargs):
    return _L.resize(input, size=size, **kwargs)


def bilinear_interp(input, out_size_x, out_size_y, **kwargs):
    return _L.bilinear_interp(input, out_h=out_size_y,
                              out_w=out_size_x, **kwargs)


def switch_order(input, reshape_order=None, **kwargs):
    """switch_order_layer: NCHW <-> NHWC (the only two orders the
    reference SwitchOrderLayer supports)."""
    if reshape_order in (None, [0, 2, 3, 1], (0, 2, 3, 1)):
        return _L.switch_order(input, to_nhwc=True, **kwargs)
    if reshape_order in ([0, 3, 1, 2], (0, 3, 1, 2)):
        return _L.switch_order(input, to_nhwc=False, **kwargs)
    raise ValueError("switch_order supports NCHW<->NHWC orders "
                     "[0,2,3,1] / [0,3,1,2], got %r" % (reshape_order,))


def scale_shift(input, param_attr=None, bias_attr=None, **kwargs):
    return _L.scale_shift(input, param_attr=param_attr,
                          bias_attr=bias_attr, **kwargs)


def scale_sub_region(input, indices, value=1.0, **kwargs):
    return _L.scale_sub_region(input, indices, value=value, **kwargs)


def sum_to_one_norm(input, **kwargs):
    return _tag(_L.sum_to_one_norm(input), input)


def row_l2_norm(input, **kwargs):
    return _tag(_L.row_l2_norm(input), input)


def cross_channel_norm(input, param_attr=None, **kwargs):
    """Per-pixel L2 norm across channels x learned per-channel scale
    (reference cross_channel_norm_layer / CrossChannelNormLayer,
    SSD)."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper("cross_channel_norm", **kwargs)
    c = input.shape[1]
    scale = helper.create_parameter(
        param_attr, shape=[1, c, 1, 1], dtype=input.dtype)
    normed = _L.l2_normalize(input, axis=1)
    return _L.elementwise_mul(normed, scale)


def prelu(input, param_attr=None, **kwargs):
    return _L.prelu(input, param_attr=param_attr, **kwargs)


def dropout(input, dropout_rate=0.5, **kwargs):
    return _tag(_L.dropout(input, dropout_prob=dropout_rate, **kwargs),
                input)


def clip(input, min, max, **kwargs):
    return _tag(_L.clip(input, min=min, max=max), input)


# ---- elementwise / math layers --------------------------------------

def addto(input, act=None, bias_attr=None, **kwargs):
    inputs = input if isinstance(input, (list, tuple)) else [input]
    out = _L.sums(list(inputs))
    act_n = _act_name(act)
    out = getattr(_L, act_n)(out) if act_n else out
    return _tag(out, inputs[0])


def concat(input, act=None, **kwargs):
    out = _L.concat(list(input), axis=-1, **kwargs)
    act_n = _act_name(act)
    out = getattr(_L, act_n)(out) if act_n else out
    return _tag(out, input[0])


def interpolation(input, weight, **kwargs):
    """interpolation_layer(input=[x1, x2], weight): w*x1+(1-w)*x2."""
    x1, x2 = input
    return _tag(_L.interpolation(x1, x2, weight), x1)


def linear_comb(weights, vectors, size, **kwargs):
    return _L.linear_comb(weights, vectors, size, **kwargs)


def slope_intercept(input, slope=1.0, intercept=0.0, **kwargs):
    return _tag(_L.slope_intercept(input, slope, intercept), input)


def power(input, weight, **kwargs):
    return _tag(_L.power(input, weight), input)


def scaling(input, weight, **kwargs):
    """scaling_layer: per-row scalar weight * input."""
    return _tag(_L.elementwise_mul(input, weight), input)


def trans(input, **kwargs):
    return _L.trans(input)


def repeat(input, num_repeats, as_row_vector=True, act=None, **kwargs):
    out = _L.repeat(input, num_repeats, as_row_vector=as_row_vector)
    act_n = _act_name(act)
    return getattr(_L, act_n)(out) if act_n else out


def expand(input, expand_as, expand_level="non-seq", **kwargs):
    """expand_layer: broadcast per-sequence rows to per-timestep
    (padded analog of the LoD expand)."""
    out = _L.sequence_expand(input, expand_as,
                             y_length=_length_of(expand_as), **kwargs)
    return _tag(out, expand_as)


def dot_prod(input1, input2, **kwargs):
    """Per-row dot product [B, 1] (reference dot_prod_layer)."""
    prod = _L.elementwise_mul(input1, input2)
    return _L.reduce_sum(prod, dim=-1, keep_dim=True)


def out_prod(input1, input2, **kwargs):
    return _L.out_prod(input1, input2, **kwargs)


def cos_sim(a, b, scale=1, **kwargs):
    out = _L.cos_sim(a, b, **kwargs)
    return _L.scale(out, scale=float(scale)) if scale != 1 else out


def l2_distance(x, y, **kwargs):
    return _L.l2_distance(x, y, **kwargs)


def multiplex(input, **kwargs):
    """multiplex_layer: input[0] is the per-row index layer, the rest
    are candidates."""
    index, cands = input[0], list(input[1:])
    return _L.multiplex(cands, index, **kwargs)


def gated_unit(input, size, act=None, gate_param_attr=None,
               gate_bias_attr=None, inproj_param_attr=None,
               inproj_bias_attr=None, **kwargs):
    return _L.gated_unit(input, size, act=_act_name(act),
                         gate_param_attr=gate_param_attr,
                         gate_bias_attr=gate_bias_attr,
                         inproj_param_attr=inproj_param_attr,
                         inproj_bias_attr=inproj_bias_attr, **kwargs)


def factorization_machine(input, factor_size, param_attr=None,
                          **kwargs):
    return _L.factorization_machine(input, factor_size,
                                    param_attr=param_attr, **kwargs)


def conv_shift(a, b, **kwargs):
    return _L.conv_shift(a, b, **kwargs)


def row_conv(input, context_len, act=None, param_attr=None, **kwargs):
    out = _L.row_conv(input, future_context_size=context_len - 1,
                      param_attr=param_attr, **kwargs)
    act_n = _act_name(act)
    return _tag(getattr(_L, act_n)(out) if act_n else out, input)


# ---- sequence layers -------------------------------------------------

def pooling(input, pooling_type=None, **kwargs):
    """Sequence pooling over the time axis (v2 pooling layer). On a
    sub-sequence input ([B, S, T, ...] + sub-lengths) it pools the
    INNERMOST level -> [B, S, ...] still tagged as an outer sequence —
    the reference's sequence_pool over a 2-level LoD; pool again for
    [B, ...]."""
    ptype = getattr(pooling_type, "name", None) or "max"
    sublen = getattr(input, "_v2_sublen", None)
    if sublen is not None:
        out = _L.nested_sequence_pool(input, sublen, pool_type=ptype,
                                      **kwargs)
        out._v2_length = input._v2_length
        return out
    return _L.sequence_pool(input, ptype, length=_length_of(input),
                            **kwargs)


def last_seq(input, **kwargs):
    return _L.sequence_last_step(input, length=_length_of(input),
                                 **kwargs)


def first_seq(input, **kwargs):
    return _L.sequence_first_step(input, length=_length_of(input),
                                  **kwargs)


def seq_concat(a, b, **kwargs):
    """Per-sample time concatenation. With known lengths the packed op
    shifts b behind a's valid prefix; otherwise a plain time-axis
    concat (full-length sequences)."""
    la, lb = _length_of(a), _length_of(b)
    if la is not None and lb is not None:
        out, ln = _L.sequence_concat_packed(a, b, la, lb)
        out._v2_length = ln
        return out
    return _L.sequence_concat([a, b], **kwargs)


def seq_reshape(input, reshape_size, **kwargs):
    out, new_len = _L.sequence_reshape(input, new_dim=reshape_size,
                                       length=_length_of(input),
                                       **kwargs)
    if new_len is not None:
        out._v2_length = new_len
    return out


def seq_slice(input, starts=0, ends=None, **kwargs):
    ends = ends if ends is not None else input.shape[1]
    return _L.sequence_slice(input, starts, ends - starts, **kwargs)


def sub_seq(input, offsets, sizes, max_size=None, **kwargs):
    out, new_len = _L.sub_seq(input, offsets, sizes,
                              max_size or input.shape[1], **kwargs)
    out._v2_length = new_len
    return out


def sub_nested_seq(input, selected_indices, sub_len=None, **kwargs):
    """sub_nested_seq_layer: select sub-sequences by index. The
    reference carried sub-lengths in the nested LoD; the padded analog
    defaults every sub-sequence to the full inner time axis."""
    if sub_len is None:
        t = input.shape[2]
        s_dim = input.shape[1]
        sub_len = _L.fill_constant_batch_size_like(
            input, [-1, s_dim], "int64", t)
    return _L.sub_nested_seq(input, sub_len, selected_indices,
                             **kwargs)


def kmax_seq_score(input, beam_size=1, **kwargs):
    return _L.kmax_seq_score(input, length=_length_of(input),
                             beam_size=beam_size, **kwargs)


def maxid(input, **kwargs):
    out, idx = _L.topk(input, k=1, **kwargs)
    return idx


max_id = maxid


def eos(input, eos_id, **kwargs):
    return _L.eos(input, eos_id, **kwargs)


def sampling_id(input, **kwargs):
    from ..layer_helper import LayerHelper
    helper = LayerHelper("sampling_id", **kwargs)
    out = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="sampling_id",
                     inputs={"X": [_first(input).name]},
                     outputs={"Out": [out.name]}, attrs={})
    return out


# ---- recurrent -------------------------------------------------------

def lstmemory(input, size=None, reverse=False, act=None,
              param_attr=None, bias_attr=None, **kwargs):
    """Fused LSTM over a [B, T, 4H] projected sequence (reference
    lstmemory: input must be width 4*size). Returns hidden states
    [B, T, H]."""
    size = size or input.shape[-1] // 4
    h, c = _L.dynamic_lstm(input, size, length=_length_of(input),
                           is_reverse=reverse, param_attr=param_attr,
                           bias_attr=bias_attr, **kwargs)
    return _tag(h, input)


def grumemory(input, size=None, reverse=False, act=None,
              param_attr=None, bias_attr=None, **kwargs):
    size = size or input.shape[-1] // 3
    h = _L.dynamic_gru(input, size, length=_length_of(input),
                       is_reverse=reverse, param_attr=param_attr,
                       bias_attr=bias_attr, **kwargs)
    return _tag(h, input)


class StaticInput:
    """Non-time-varying input to recurrent_group (reference
    StaticInput)."""

    def __init__(self, input, is_seq=False, size=None):
        self.input = input
        self.is_seq = is_seq
        self.size = size


_GROUP_STACK = []


def memory(name=None, size=None, boot_layer=None, **kwargs):
    """Step memory inside recurrent_group (reference memory()). Returns
    the previous step's value; the step function updates it by calling
    ``update_memory(mem, new)`` (explicit here — the reference's
    implicit update-by-name relies on its global layer-name registry;
    documented divergence) or by returning it from lstm_step/gru_step.
    """
    from ..core import unique_name as _un
    if not _GROUP_STACK:
        raise RuntimeError("memory() outside a recurrent_group step")
    rnn, outer_anchor = _GROUP_STACK[-1]
    if boot_layer is None:
        # zero boot, batch-sized like the OUTER sequence input: the
        # init is read by the scan setup in the parent block, so the
        # fill op must live there, not in the step sub-block
        parent = rnn.parent_block
        boot_layer = parent.create_var(
            name=_un.generate("v2.memory_boot"), dtype="float32",
            shape=(-1, size), stop_gradient=True)
        parent.append_op(
            "fill_constant_batch_size_like",
            inputs={"Input": [outer_anchor.name]},
            outputs={"Out": [boot_layer.name]},
            attrs={"shape": [-1, size], "dtype": "float32",
                   "value": 0.0, "input_dim_idx": 0,
                   "output_dim_idx": 0})
    if hasattr(rnn, "state"):          # BeamSearchDecoder context
        mem = rnn.state(boot_layer)
    else:
        mem = rnn.memory(init=boot_layer)
    mem._v2_memory = True
    return mem


def update_memory(mem, new):
    if not _GROUP_STACK:
        raise RuntimeError("update_memory outside a recurrent_group")
    rnn, _ = _GROUP_STACK[-1]
    if hasattr(rnn, "update_state"):   # BeamSearchDecoder context
        rnn.update_state(mem, new)
    else:
        rnn.update_memory(mem, new)
    return new


def recurrent_group(step, input, reverse=False, **kwargs):
    """Run ``step`` over the time axis (reference recurrent_group /
    RecurrentLayerGroup). ``input``: sequence vars ([B, T, D]) sliced
    per step, or StaticInput passed whole. The step's return value(s)
    become [B, T, ...] outputs."""
    inputs = input if isinstance(input, (list, tuple)) else [input]
    rnn = _L.StaticRNN(is_reverse=reverse)
    seq_vars = [v for v in inputs if not isinstance(v, StaticInput)]
    outer_anchor = seq_vars[0] if seq_vars else None
    with rnn.step():
        step_args = []
        for v in inputs:
            if isinstance(v, StaticInput):
                step_args.append(v.input)
            else:
                step_args.append(rnn.step_input(v))
        _GROUP_STACK.append((rnn, outer_anchor))
        try:
            outs = step(*step_args)
        finally:
            _GROUP_STACK.pop()
        outs_t = outs if isinstance(outs, (list, tuple)) else [outs]
        for o in outs_t:
            rnn.step_output(o)
    result = rnn()
    result_t = result if isinstance(result, (list, tuple)) else [result]
    src = seq_vars[0] if seq_vars else None
    if src is not None:
        for r in result_t:
            _tag(r, src)
    return result if not isinstance(result, (list, tuple)) else \
        (result_t[0] if len(result_t) == 1 else result_t)


def lstm_step(input, state, size=None, act=None, gate_act=None,
              state_act=None, **kwargs):
    """One LSTM step inside recurrent_group (reference lstm_step_layer):
    input = x projection [B, 4H], state = cell memory. Returns hidden;
    updates the cell memory in place."""
    from ..layer_helper import LayerHelper
    size = size or state.shape[-1]
    helper = LayerHelper("v2_lstm_step", **kwargs)
    h = helper.create_tmp_variable(input.dtype)
    c = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="lstm_unit",
                     inputs={"X": [input.name],
                             "C_prev": [state.name]},
                     outputs={"H": [h.name], "C": [c.name]})
    update_memory(state, c)
    return h


def gru_step(input, output_mem, size=None, act=None, gate_act=None,
             **kwargs):
    """One GRU step inside recurrent_group (reference gru_step_layer):
    input = x projection [B, 3H], output_mem = previous hidden."""
    size = size or output_mem.shape[-1]
    h, _gate, _reset = _L.gru_unit(input, output_mem, size, **kwargs)
    update_memory(output_mem, h)
    return h


gru_step_naive = gru_step


def get_output(input, arg_name=None, **kwargs):
    """get_output_layer: select one of a multi-output layer's results
    (here: tuples are first-class, so this is indexing)."""
    if isinstance(input, (list, tuple)):
        idx = {"state": 1, "hidden": 0}.get(arg_name, 0)
        return input[idx]
    return input


def recurrent(input, act=None, reverse=False, param_attr=None,
              bias_attr=None, **kwargs):
    """Simple full-matrix recurrent layer (reference recurrent_layer):
    h_t = act(x_t + W h_{t-1})."""
    size = input.shape[-1]

    def step(x):
        prev = memory(size=size)
        h = fc([x, prev], size, act=act or __import__(
            "paddle_tpu.v2.activation", fromlist=["Tanh"]).Tanh(),
            param_attr=param_attr, bias_attr=bias_attr)
        update_memory(prev, h)
        return h

    return recurrent_group(step, input, reverse=reverse)


def beam_search(step, input, bos_id, eos_id, beam_size=4,
                max_length=16, **kwargs):
    """v2 beam-search generation (reference beam_search): ``step``
    receives (token_embedding_maker-style) the current token var and
    any StaticInputs, and must return the per-step softmax/logits var.
    Implemented on the generic BeamSearchDecoder (see
    layers/beam_search.py; the same engine drives the seq2seq and
    transformer generate paths). Returns (ids, lengths, scores)."""
    statics = [v for v in (input if isinstance(input, (list, tuple))
                           else [input])]
    bs = _L.BeamSearchDecoder(beam_size=beam_size, max_len=max_length,
                              bos_id=bos_id, eos_id=eos_id)
    outer_anchor = next((v.input for v in statics
                         if isinstance(v, StaticInput)), None)
    with bs.step():
        tok = bs.token()
        args = []
        for v in statics:
            if isinstance(v, StaticInput):
                args.append(bs.state(v.input))
            else:
                args.append(v)
        _GROUP_STACK.append((bs, outer_anchor))
        try:
            logits = step(tok, *args)
        finally:
            _GROUP_STACK.pop()
        bs.set_logits(logits)
    return bs()


# ---- projections / operators + mixed --------------------------------

class _Projection:
    """Lazy projection: applied when mixed_layer assembles its sum."""

    def __init__(self, fn, src):
        self.fn = fn
        self.src = src

    def apply(self, size):
        return self.fn(size)


def full_matrix_projection(input, size=0, param_attr=None, **kwargs):
    return _Projection(
        lambda sz: _L.fc(input, sz, bias_attr=False,
                         param_attr=param_attr,
                         num_flatten_dims=2 if len(input.shape or ())
                         >= 3 else 1), input)


def trans_full_matrix_projection(input, size=0, param_attr=None,
                                 **kwargs):
    """W^T projection (reference trans_full_matrix_projection — weight
    sharing with a forward projection via transpose)."""
    def fn(sz):
        from ..layer_helper import LayerHelper
        helper = LayerHelper("trans_fm_proj")
        w = helper.create_parameter(param_attr,
                                    shape=[sz, input.shape[-1]],
                                    dtype=input.dtype)
        wt = _L.trans(w)
        return _L.matmul(input, wt)
    return _Projection(fn, input)


def table_projection(input, size=0, param_attr=None, **kwargs):
    entry = _input_types().get(input.name)
    vocab = entry[0].dim if entry else None
    if getattr(input, "_v2_value", None) is not None:
        return _Projection(
            lambda sz: _sparse_float_rowsum(input, sz, param_attr),
            input)
    return _Projection(
        lambda sz: _L.embedding(input, size=[vocab, sz],
                                param_attr=param_attr), input)


def identity_projection(input, offset=None, size=None, **kwargs):
    def fn(sz):
        if offset is None:
            return input
        end = offset + (size or sz)
        return _L.slice(input, axes=[len(input.shape) - 1],
                        starts=[offset], ends=[end])
    return _Projection(fn, input)


def slice_projection(input, slices, **kwargs):
    def fn(sz):
        parts = [_L.slice(input, axes=[len(input.shape) - 1],
                          starts=[s], ends=[e]) for s, e in slices]
        return _L.concat(parts, axis=-1)
    return _Projection(fn, input)


def scaling_projection(input, param_attr=None, **kwargs):
    def fn(sz):
        from ..layer_helper import LayerHelper
        helper = LayerHelper("scaling_proj")
        w = helper.create_parameter(param_attr, shape=[1],
                                    dtype=input.dtype)
        return _L.elementwise_mul(input, w)
    return _Projection(fn, input)


def dotmul_projection(input, param_attr=None, **kwargs):
    def fn(sz):
        from ..layer_helper import LayerHelper
        helper = LayerHelper("dotmul_proj")
        w = helper.create_parameter(param_attr,
                                    shape=[input.shape[-1]],
                                    dtype=input.dtype)
        return _L.elementwise_mul(input, w)
    return _Projection(fn, input)


def dotmul_operator(a, b, scale=1.0, **kwargs):
    out = _L.elementwise_mul(a, b)
    return _Projection(
        lambda sz, o=out: _L.scale(o, scale=scale)
        if scale != 1.0 else o, a)


def context_projection(input, context_len, context_start=None,
                       **kwargs):
    """Parameter-free context window: concat of time-shifted copies
    (reference ContextProjection)."""
    start = context_start if context_start is not None else \
        -(context_len // 2)

    def fn(sz):
        t = input.shape[1]
        parts = []
        for off in range(start, start + context_len):
            if off == 0:
                parts.append(input)
                continue
            if off < 0:
                padded = _L.pad(input, paddings=[0, 0, -off, 0, 0, 0])
                parts.append(_L.slice(padded, axes=[1], starts=[0],
                                      ends=[t]))
            else:
                padded = _L.pad(input, paddings=[0, 0, 0, off, 0, 0])
                parts.append(_L.slice(padded, axes=[1], starts=[off],
                                      ends=[t + off]))
        return _L.concat(parts, axis=-1)
    return _Projection(fn, input)


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, param_attr=None, **kwargs):
    return _Projection(
        lambda sz: _L.conv2d(input, num_filters=num_filters,
                             filter_size=filter_size, stride=stride,
                             padding=padding, param_attr=param_attr,
                             bias_attr=False), input)


def conv_operator(img, filter, filter_size, num_filters,
                  num_channels=None, stride=1, padding=0,
                  filter_size_y=None, stride_y=None, padding_y=None,
                  **kwargs):
    """conv_operator: data-dependent filter conv inside mixed — the
    filter is another LAYER's output (one filter bank per batch row),
    not a parameter (reference gserver/layers/ConvOperator.h:31,
    ConvOperator.cpp:59 — per-row conv loop; config api
    trainer_config_helpers conv_operator). The filter layer's width
    must be num_filters*num_channels*kh*kw; output is the flattened
    [B, num_filters*oh*ow] feature map, summable inside mixed()."""
    kh = filter_size_y if filter_size_y is not None else filter_size
    kw = filter_size
    sy = stride_y if stride_y is not None else stride
    py = padding_y if padding_y is not None else padding

    def fn(sz):
        x = img
        if len(x.shape) == 2:
            if num_channels is None:
                raise ValueError(
                    "conv_operator on a flat input needs num_channels")
            hw = x.shape[-1] // num_channels
            side = int(round(float(hw) ** 0.5))
            if side * side != hw:
                raise ValueError(
                    "conv_operator cannot infer a square image from "
                    "width %d / %d channels" % (x.shape[-1],
                                                num_channels))
            x = _L.reshape(x, [-1, num_channels, side, side])
        c = num_channels if num_channels is not None else x.shape[1]
        f = filter
        if len(f.shape) != 5:
            f = _L.reshape(f, [-1, num_filters, c, kh, kw])
        out = _L.batch_conv2d(x, f, stride=[sy, stride],
                              padding=[py, padding])
        return _L.reshape(out, [out.shape[0],
                                int(np.prod(out.shape[1:]))])
    return _Projection(fn, img)


def mixed(size, input=None, act=None, bias_attr=None, **kwargs):
    """mixed_layer: sum of projections/operators, then bias + act."""
    projs = input if isinstance(input, (list, tuple)) else [input]
    outs = [p.apply(size) if isinstance(p, _Projection) else p
            for p in projs]
    out = outs[0] if len(outs) == 1 else _L.sums(list(outs))
    if bias_attr is not False and bias_attr is not None:
        from ..layer_helper import LayerHelper
        helper = LayerHelper("mixed_bias")
        b = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                    shape=[size], dtype=out.dtype,
                                    is_bias=True)
        out = _L.elementwise_add(out, b)
    act_n = _act_name(act)
    out = getattr(_L, act_n)(out) if act_n else out
    return _tag(out, projs[0].src if isinstance(projs[0], _Projection)
                else projs[0])


# ---- detection -------------------------------------------------------

def priorbox(input, image, min_size, max_size=None, aspect_ratio=None,
             variance=None, **kwargs):
    return _L.prior_box(input, image, min_sizes=list(min_size),
                        max_sizes=list(max_size or []),
                        aspect_ratios=list(aspect_ratio or []),
                        variances=list(variance or
                                       [0.1, 0.1, 0.2, 0.2]), **kwargs)


def multibox_loss(input_loc, input_conf, priorbox, gt_box, gt_label,
                  gt_count, num_classes=None, overlap_threshold=0.5,
                  neg_pos_ratio=3.0, **kwargs):
    """SSD loss. ``priorbox`` is the (boxes, variances) pair returned
    by priorbox(); the reference's single LoD ``label`` input becomes
    the padded (gt_box [N,G,4], gt_label [N,G], gt_count [N]) triple
    (SURVEY §5.7 padded-batch convention). num_classes is implied by
    input_conf's last dim and accepted for signature parity."""
    boxes, variances = priorbox
    return _L.multibox_loss(input_loc, input_conf,
                            _flatten_priors(boxes),
                            _flatten_priors(variances),
                            gt_box, gt_label, gt_count,
                            overlap_threshold=overlap_threshold,
                            neg_pos_ratio=neg_pos_ratio, **kwargs)


def _flatten_priors(v):
    """[H, W, P, 4] prior grids -> [H*W*P, 4] (the fluid detection ops
    take flat prior lists)."""
    if len(v.shape or ()) > 2:
        return _L.reshape(v, [-1, 4])
    return v


def detection_output(input_loc, input_conf, priorbox, num_classes=None,
                     nms_threshold=0.45, keep_top_k=200, **kwargs):
    """SSD inference head. ``priorbox`` = (boxes, variances) from
    priorbox(); input_conf holds post-softmax scores."""
    boxes, variances = priorbox
    return _L.detection_output(input_loc, input_conf,
                               _flatten_priors(boxes),
                               _flatten_priors(variances),
                               nms_threshold=nms_threshold,
                               keep_top_k=keep_top_k, **kwargs)


def roi_pool(input, rois, pooled_width, pooled_height,
             spatial_scale=1.0, **kwargs):
    return _L.roi_pool(input, rois, pooled_height=pooled_height,
                       pooled_width=pooled_width,
                       spatial_scale=spatial_scale, **kwargs)


# ---- costs -----------------------------------------------------------

def classification_cost(input, label, **kwargs):
    """softmax_with_cross_entropy mean (v2 classification_cost: the
    input is pre-softmax unless already activated; reference applies
    softmax inside the cost when the layer's act is Softmax — here the
    convention is: pass logits OR softmax output, cross_entropy picks
    the right path by checking the producing layer)."""
    if getattr(input, "_v2_softmaxed", False):
        return _L.mean(_L.cross_entropy(input, label, **kwargs))
    return _L.mean(_L.softmax_with_cross_entropy(input, label, **kwargs))


def cross_entropy_cost(input, label, **kwargs):
    return _L.mean(_L.cross_entropy(input, label, **kwargs))


cross_entropy = cross_entropy_cost


def cross_entropy_with_selfnorm_cost(input, label,
                                     softmax_selfnorm_alpha=0.1,
                                     **kwargs):
    return _L.mean(_L.cross_entropy_with_selfnorm(
        input, label, softmax_selfnorm_alpha))


cross_entropy_with_selfnorm = cross_entropy_with_selfnorm_cost


def multi_binary_label_cross_entropy_cost(input, label, **kwargs):
    return _L.mean(_L.multi_binary_label_cross_entropy(input, label))


multi_binary_label_cross_entropy = multi_binary_label_cross_entropy_cost


def cross_entropy_over_beam(input, **kwargs):
    """input: list of (scores, ids, gold) triples (see
    layers/legacy.py cross_entropy_over_beam)."""
    return _L.mean(_L.cross_entropy_over_beam(input))


def regression_cost(input, label, **kwargs):
    return _L.mean(_L.square_error_cost(input, label, **kwargs))


mse_cost = regression_cost


def square_error_cost(input, label, **kwargs):
    return _L.square_error_cost(input, label, **kwargs)


def rank_cost(left, right, label, **kwargs):
    return _L.mean(_L.rank_loss(left, right, label, **kwargs))


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, **kwargs):
    return _L.mean(_L.lambda_cost(input, score,
                                  length=_length_of(input),
                                  NDCG_num=NDCG_num,
                                  max_sort_size=max_sort_size))


def sum_cost(input, **kwargs):
    return _L.sum_cost(input)


def huber_regression_cost(input, label, delta=1.0, **kwargs):
    return _L.mean(_L.huber_loss(input, label, delta=delta, **kwargs))


def huber_classification_cost(input, label, **kwargs):
    from ..layer_helper import LayerHelper
    helper = LayerHelper("modified_huber", **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="modified_huber_loss",
                     inputs={"X": [input.name], "Y": [label.name]},
                     outputs={"Out": [out.name]})
    return _L.mean(out)


def smooth_l1_cost(input, label, **kwargs):
    return _L.mean(_L.smooth_l1(input, label, **kwargs))


def hsigmoid(input, label, num_classes, param_attr=None,
             bias_attr=None, **kwargs):
    return _L.mean(_L.hsigmoid(_first(input), label, num_classes,
                               param_attr=param_attr,
                               bias_attr=bias_attr, **kwargs))


def nce(input, label, num_classes, num_neg_samples=10,
        param_attr=None, bias_attr=None, **kwargs):
    return _L.mean(_L.nce(_first(input), label, num_classes,
                          num_neg_samples=num_neg_samples,
                          param_attr=param_attr, bias_attr=bias_attr,
                          **kwargs))


def ctc(input, label, size=None, label_length=None, **kwargs):
    llen = _length_of(input)
    if llen is None:  # full-length logits (no padding)
        llen = _L.fill_constant_batch_size_like(
            input, [-1], "int64", input.shape[1])
    tlen = label_length if label_length is not None else \
        _length_of(label)
    if tlen is None:
        tlen = _L.fill_constant_batch_size_like(
            label, [-1], "int64", label.shape[1])
    return _L.mean(_L.warpctc(input, label, logits_length=llen,
                              label_length=tlen, **kwargs))


warp_ctc = ctc


def crf(input, label, size=None, param_attr=None, **kwargs):
    ll = _L.linear_chain_crf(input, label, length=_length_of(input),
                             param_attr=param_attr, **kwargs)
    return _L.mean(_L.scale(ll, scale=-1.0))


def crf_decoding(input, size=None, param_attr=None, **kwargs):
    return _L.crf_decoding(input, param_attr,
                           length=_length_of(input), **kwargs)


# ---- group shorthands (kept from the earlier surface) ---------------

def lstmemory_group(input, size, reverse=False, **kwargs):
    """v2 simple_lstm-style group over a sequence input."""
    out = _nets.simple_lstm(input, size, length=_length_of(input),
                            is_reverse=reverse, **kwargs)
    return _tag(out, input)


def gru_group(input, size, reverse=False, **kwargs):
    out = _nets.simple_gru(input, size, length=_length_of(input),
                           is_reverse=reverse, **kwargs)
    return _tag(out, input)


def parse_network(*outputs):
    """v2 topology hook — programs ARE the topology here."""
    return list(outputs)


# *_layer aliases (the trainer_config_helpers spellings)
_ALIASES = {}
for _name in list(globals()):
    _obj = globals()[_name]
    if callable(_obj) and not _name.startswith("_") and _name not in (
            "StaticInput", "ParamAttr", "memory", "update_memory",
            "parse_network", "np"):
        _ALIASES[_name + "_layer"] = _obj
globals().update(_ALIASES)
