"""v2 activation descriptors (reference ``python/paddle/v2/activation.py``
wrapping config BaseActivation classes)."""

__all__ = ["Linear", "Relu", "Sigmoid", "Tanh", "Softmax", "Exp",
           "Identity"]


class _Act:
    name = None

    def __repr__(self):
        return "activation.%s" % type(self).__name__


class Linear(_Act):
    name = None


Identity = Linear


class Relu(_Act):
    name = "relu"


class Sigmoid(_Act):
    name = "sigmoid"


class Tanh(_Act):
    name = "tanh"


class Softmax(_Act):
    name = "softmax"


class Exp(_Act):
    name = "exp"
