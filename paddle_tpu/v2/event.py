"""v2 training events (reference ``python/paddle/v2/event.py``) — the
fluid-side Trainer already emits this exact protocol; re-exported under
the v2 names."""

from ..trainer import (BeginIteration, EndIteration, BeginPass,  # noqa
                       EndPass)

__all__ = ["BeginIteration", "EndIteration", "BeginPass", "EndPass"]
