"""v2 pooling descriptors (reference ``python/paddle/v2/pooling.py``)."""

__all__ = ["Max", "Avg", "Sum"]


class _Pool:
    name = None


class Max(_Pool):
    name = "max"


class Avg(_Pool):
    name = "average"


class Sum(_Pool):
    name = "sum"
