"""v2 optimizers (reference ``python/paddle/v2/optimizer.py``): thin
constructors over the fluid-style optimizer classes (the v2 surface
took regularization/model_average kwargs; regularization maps through,
model averaging is optimizer.ModelAverage)."""

from .. import optimizer as _opt
from .. import regularizer as _reg

__all__ = ["Momentum", "Adam", "AdaGrad", "RMSProp", "AdaDelta",
           "Optimizer"]


def _regularization(rate):
    return _reg.L2Decay(rate) if rate else None


def Momentum(momentum=0.9, learning_rate=0.01,
             regularization_rate=0.0, **kwargs):
    return _opt.Momentum(learning_rate=learning_rate, momentum=momentum,
                         regularization=_regularization(
                             regularization_rate))


def Adam(learning_rate=1e-3, beta1=0.9, beta2=0.999, epsilon=1e-8,
         regularization_rate=0.0, **kwargs):
    return _opt.Adam(learning_rate=learning_rate, beta1=beta1,
                     beta2=beta2, epsilon=epsilon,
                     regularization=_regularization(regularization_rate))


def AdaGrad(learning_rate=1e-2, regularization_rate=0.0, **kwargs):
    return _opt.Adagrad(learning_rate=learning_rate,
                        regularization=_regularization(
                            regularization_rate))


def RMSProp(learning_rate=1e-2, regularization_rate=0.0, **kwargs):
    return _opt.RMSProp(learning_rate=learning_rate,
                        regularization=_regularization(
                            regularization_rate))


def AdaDelta(learning_rate=1.0, regularization_rate=0.0, **kwargs):
    return _opt.AdaDelta(learning_rate=learning_rate,
                         regularization=_regularization(
                             regularization_rate))


Optimizer = _opt.Optimizer
