"""v2 trainer surface (reference ``python/paddle/v2/trainer.py:37-207``
SGD(cost, parameters, update_equation).train(reader, num_passes,
event_handler, feeding)): the legacy entry point, lowered onto the
fluid-style Trainer/Executor. The reader yields BATCHES of sample
tuples (make them with ``paddle.batch``); ``feeding`` maps data-layer
name -> tuple index."""

import numpy as np

from ..core.framework import (default_main_program,
                              default_startup_program)
from ..data_feeder import DataFeeder
from ..trainer import Trainer as _FluidTrainer
from . import layer as _v2layer

__all__ = ["SGD"]


def _build_feeder(feeding, sample_width, program=None):
    """DataFeeder from the v2 feeding map + registered input types."""
    if feeding is None:
        raise ValueError("v2 SGD needs feeding={layer_name: index}")
    order = sorted(feeding.items(), key=lambda kv: kv[1])
    if len(order) != sample_width:
        raise ValueError("feeding has %d slots but samples have %d "
                         "fields" % (len(order), sample_width))
    feed_list = []
    for name, _ in order:
        entry = _v2layer._input_types(program).get(name)
        if entry is None:
            raise KeyError("unknown data layer %r in feeding" % name)
        typ, length = entry
        if getattr(typ, "is_sparse_pair", False):
            spec = {"kind": "sparse", "name": name,
                    "values": name + "@value", "depth": typ.seq_type}
            if typ.seq_type >= 1:
                spec["len"] = name + "@len"
            if typ.seq_type == 2:
                spec["sublen"] = name + "@sublen"
            feed_list.append(spec)
        elif getattr(typ, "is_nested", False):
            feed_list.append({"kind": "nested", "name": name,
                              "len": name + "@len",
                              "sublen": name + "@sublen",
                              "dtype": typ.dtype})
        elif typ.is_seq:
            feed_list.append((name, length.name))
        else:
            feed_list.append(name)
    return DataFeeder(feed_list)


class SGD:
    def __init__(self, cost, parameters, update_equation,
                 extra_layers=None, is_local=True):
        self.__topology_in_use__ = cost
        self._cost = cost
        self._parameters = parameters
        self._main = default_main_program()
        self._startup = default_startup_program()
        update_equation.minimize(cost, startup_program=self._startup)
        self._trainer = None

    def train(self, reader, num_passes=1, event_handler=None,
              feeding=None):
        sample = next(iter(reader()))[0]
        feeder = _build_feeder(feeding, len(sample), self._main)
        if self._trainer is None:
            self._trainer = _FluidTrainer(
                self._cost, feeder=feeder, main_program=self._main,
                startup_program=self._startup)
        else:
            self._trainer.feeder = feeder
        self._trainer.train(reader, num_passes=num_passes,
                            event_handler=event_handler)

    def save_parameter_to_tar(self, f):
        """Save the trained parameters to an open binary file as a tar
        checkpoint (reference ``trainer.py`` SGD.save_parameter_to_tar
        — the v2 event-handler save idiom)."""
        self._parameters.to_tar(f)

    def test(self, reader, feeding=None):
        """Mean cost over a test reader (v2 SGD.test)."""
        sample = next(iter(reader()))[0]
        feeder = _build_feeder(feeding, len(sample), self._main)
        if self._trainer is None:
            self._trainer = _FluidTrainer(
                self._cost, feeder=feeder, main_program=self._main,
                startup_program=self._startup)
        from ..io import prune_program
        pruned = prune_program(self._main, [self._cost.name])
        self._trainer.startup()
        exe = self._trainer.exe
        total, count = 0.0, 0
        for batch in reader():
            out, = exe.run(pruned, feed=feeder.feed(batch),
                           fetch_list=[self._cost.name])
            total += float(np.asarray(out).mean())
            count += 1

        class _Result:
            cost = total / max(count, 1)
            metrics = {"cost": total / max(count, 1)}
        return _Result()
