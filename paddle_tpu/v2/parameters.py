"""v2 Parameters handle (reference ``python/paddle/v2/parameters.py``):
a named view over the trained parameter values. Here parameters live in
the global Scope; ``create(cost)`` snapshots the topology's parameter
names and the handle reads/writes the scope.

Tar checkpoints (reference ``parameters.py:328 to_tar``, ``:358
from_tar``, ``:387 init_from_tar``): one tar member per parameter with
the reference's 16-byte header (``struct.pack("IIQ", version,
value_size, count)``) followed by the raw value bytes, plus a
``<name>.conf`` JSON member in place of the reference's
``<name>.protobuf`` ParameterConfig (this design is proto-less,
SURVEY N26)."""

import io
import json
import struct
import tarfile

import numpy as np

from ..core.scope import global_scope

__all__ = ["Parameters", "create"]


class Parameters:
    def __init__(self, names=(), _local=None):
        self._names = list(names)
        # from_tar() products are DETACHED from the scope: values live
        # in this dict until pushed via init_from_tar / set on a
        # scope-backed handle.
        self._local = _local

    def names(self):
        return list(self._names)

    def keys(self):
        return self.names()

    def __contains__(self, name):
        return name in self._names

    def get(self, name):
        if self._local is not None:
            v = self._local.get(name)
            return None if v is None else np.asarray(v)
        v = global_scope().find_var(name)
        return None if v is None else np.asarray(v)

    __getitem__ = get

    def set(self, name, value):
        if self._local is not None:
            self._local[name] = np.asarray(value)
            if name not in self._names:
                self._names.append(name)
            return
        global_scope().set_var(name, np.asarray(value))

    __setitem__ = set

    def to_dict(self):
        return {n: self.get(n) for n in self._names}

    # -- tar checkpoints (the v2 event-handler save idiom) ------------

    def serialize(self, name, f):
        """Write one parameter in the reference's wire format
        (``parameters.py:297``): header (version=0, value_size,
        element count) then raw bytes."""
        param = np.ascontiguousarray(self.get(name))
        f.write(struct.pack("IIQ", 0, param.dtype.itemsize, param.size))
        f.write(param.tobytes())

    def deserialize(self, name, f, shape, dtype):
        f.read(16)  # header; shape/dtype come from the conf member
        arr = np.frombuffer(f.read(), dtype=dtype)
        self.set(name, arr.reshape(shape))

    def to_tar(self, f):
        """Save all parameters to an open binary file object as a tar
        archive (reference ``Parameters.to_tar``). Most callers should
        use ``trainer.save_parameter_to_tar(f)``."""
        tar = tarfile.TarFile(fileobj=f, mode="w")
        for nm in self._names:
            val = self.get(nm)
            if val is None:
                continue
            buf = io.BytesIO()
            self.serialize(nm, buf)
            info = tarfile.TarInfo(name=nm)
            info.size = buf.tell()
            buf.seek(0)
            tar.addfile(info, buf)

            conf = json.dumps({"name": nm, "shape": list(val.shape),
                               "dtype": str(val.dtype)}).encode()
            info = tarfile.TarInfo(name="%s.conf" % nm)
            info.size = len(conf)
            tar.addfile(info, io.BytesIO(conf))
        tar.close()

    @staticmethod
    def from_tar(f):
        """Create a detached Parameters from a tar checkpoint
        (reference ``Parameters.from_tar``) — it holds only the values
        in the file, independent of any scope/topology."""
        params = Parameters(_local={})
        tar = tarfile.TarFile(fileobj=f, mode="r")
        confs = {}
        for finfo in tar:
            if finfo.name.endswith(".conf"):
                conf = json.loads(tar.extractfile(finfo).read().decode())
                confs[conf["name"]] = conf
        for nm, conf in confs.items():
            params.deserialize(nm, tar.extractfile(nm),
                               tuple(conf["shape"]), conf["dtype"])
        return params

    def init_from_tar(self, f, exclude_params=()):
        """Init (a subset of) THIS handle's parameters from another
        saved model (reference ``Parameters.init_from_tar``) — names
        absent from this topology are ignored."""
        tar_param = Parameters.from_tar(f)
        for nm in tar_param.names():
            if nm in exclude_params or nm not in self._names:
                continue
            cur = self.get(nm)
            val = tar_param.get(nm)
            if cur is not None and tuple(cur.shape) != tuple(val.shape):
                raise ValueError(
                    "init_from_tar: shape mismatch for %r: %s vs %s"
                    % (nm, cur.shape, val.shape))
            self.set(nm, val)


def create(cost):
    """Collect the trainable parameters reachable from ``cost``'s
    program (v2 parameters.create)."""
    costs = cost if isinstance(cost, (list, tuple)) else [cost]
    block = costs[0].block.program.global_block()
    return Parameters([p.name for p in block.all_parameters()])
