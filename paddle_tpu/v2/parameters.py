"""v2 Parameters handle (reference ``python/paddle/v2/parameters.py``):
a named view over the trained parameter values. Here parameters live in
the global Scope; ``create(cost)`` snapshots the topology's parameter
names and the handle reads/writes the scope."""

import numpy as np

from ..core.scope import global_scope

__all__ = ["Parameters", "create"]


class Parameters:
    def __init__(self, names):
        self._names = list(names)

    def names(self):
        return list(self._names)

    def keys(self):
        return self.names()

    def __contains__(self, name):
        return name in self._names

    def get(self, name):
        v = global_scope().find_var(name)
        return None if v is None else np.asarray(v)

    __getitem__ = get

    def set(self, name, value):
        global_scope().set_var(name, np.asarray(value))

    __setitem__ = set

    def to_dict(self):
        return {n: self.get(n) for n in self._names}


def create(cost):
    """Collect the trainable parameters reachable from ``cost``'s
    program (v2 parameters.create)."""
    costs = cost if isinstance(cost, (list, tuple)) else [cost]
    block = costs[0].block.program.global_block()
    return Parameters([p.name for p in block.all_parameters()])
