"""v2 input-type descriptors (reference ``python/paddle/v2/data_type.py``
re-exporting PyDataProvider2 types): each describes one feed slot's
shape/dtype/sequence-ness; the v2 trainer builds the fluid-side data
layout (padded batch + length var for sequences) from these."""

__all__ = ["InputType", "dense_vector", "integer_value",
           "dense_vector_sequence", "integer_value_sequence",
           "sparse_binary_vector"]


class InputType:
    def __init__(self, dim, seq_type, dtype):
        self.dim = dim
        self.seq_type = seq_type  # 0 = no sequence, 1 = sequence
        self.dtype = dtype

    @property
    def is_seq(self):
        return self.seq_type != 0


def dense_vector(dim):
    return InputType(dim, 0, "float32")


def integer_value(value_range):
    return InputType(value_range, 0, "int64")


def dense_vector_sequence(dim):
    return InputType(dim, 1, "float32")


def integer_value_sequence(value_range):
    return InputType(value_range, 1, "int64")


def sparse_binary_vector(dim):
    # realized as an id-sequence feed (ids of the set bits)
    return InputType(dim, 1, "int64")
