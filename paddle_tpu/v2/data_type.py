"""v2 input-type descriptors (reference ``python/paddle/v2/data_type.py``
re-exporting PyDataProvider2 types, ``python/paddle/trainer/
PyDataProvider2.py:117-245``): each describes one feed slot's
shape/dtype/sequence-ness; the v2 trainer builds the fluid-side data
layout from these.

TPU-native realizations (static shapes, SURVEY §5.7):
* sequences -> padded batch + length var;
* sub-sequences (seq_type=2, reference ``PyDataProvider2.py:198,215,
  232``) -> padded [B, S, T] + outer length [B] + sub-lengths [B, S]
  (the nested-ops convention, ops/nested_ops.py);
* sparse_binary_vector -> id-sequence (ids of the set bits);
* sparse_float_vector (reference ``py_paddle/dataprovider_converter.py:
  184`` SparseFloatScanner, ``math/CpuSparseMatrix.h:24``) -> a static
  (ids [B, K], values [B, K]) pair; consumers compute weighted
  row-sums so the [B, dim] dense form is never materialized.
"""

__all__ = ["InputType", "dense_vector", "integer_value",
           "dense_vector_sequence", "integer_value_sequence",
           "sparse_binary_vector", "sparse_float_vector",
           "sparse_binary_vector_sequence",
           "sparse_float_vector_sequence",
           "dense_vector_sub_sequence", "integer_value_sub_sequence",
           "sparse_binary_vector_sub_sequence",
           "sparse_float_vector_sub_sequence"]


class InputType:
    def __init__(self, dim, seq_type, dtype, sparse=None):
        self.dim = dim
        # 0 = no sequence, 1 = sequence, 2 = sub-sequence (nested)
        self.seq_type = seq_type
        self.dtype = dtype
        # None | "float" | "binary": sparse rows are ragged id lists
        # (+ parallel values for "float"; all-ones values synthesized
        # for "binary") padded onto a static K axis
        self.sparse = sparse

    @property
    def is_seq(self):
        return self.seq_type != 0

    @property
    def is_nested(self):
        return self.seq_type == 2

    @property
    def is_sparse_float(self):
        return self.sparse == "float"

    @property
    def is_sparse_pair(self):
        """True for the (ids, values)-pair realizations — float rows,
        and binary rows at sequence levels (where the plain id-seq
        encoding of sparse_binary_vector has no free axis left)."""
        return self.sparse in ("float", "binary")


def dense_vector(dim):
    return InputType(dim, 0, "float32")


def integer_value(value_range):
    return InputType(value_range, 0, "int64")


def dense_vector_sequence(dim):
    return InputType(dim, 1, "float32")


def integer_value_sequence(value_range):
    return InputType(value_range, 1, "int64")


def sparse_binary_vector(dim):
    # realized as an id-sequence feed (ids of the set bits)
    return InputType(dim, 1, "int64")


def sparse_float_vector(dim):
    """(ids, values) pair feed — float-weighted sparse features (CTR
    models); samples are [(id, value), ...] or ([ids], [values])."""
    return InputType(dim, 0, "int64", sparse="float")


def sparse_binary_vector_sequence(dim):
    """Sequence of sparse binary rows -> ids [B, T, K] (+ synthesized
    0/1 values) + length [B]."""
    return InputType(dim, 1, "int64", sparse="binary")


def sparse_float_vector_sequence(dim):
    """Sequence of sparse float rows -> (ids, values) [B, T, K] +
    length [B]."""
    return InputType(dim, 1, "int64", sparse="float")


def dense_vector_sub_sequence(dim):
    return InputType(dim, 2, "float32")


def integer_value_sub_sequence(value_range):
    return InputType(value_range, 2, "int64")


def sparse_binary_vector_sub_sequence(dim):
    # sub-sequences of sparse binary rows -> ids [B, S, T, K]
    return InputType(dim, 2, "int64", sparse="binary")


def sparse_float_vector_sub_sequence(dim):
    return InputType(dim, 2, "int64", sparse="float")
