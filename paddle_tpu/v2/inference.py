"""v2 inference (reference ``python/paddle/v2/inference.py:24-125``
infer(output_layer, parameters, input, feeding)): prune the topology to
the output layer and run it over a list of input samples."""

import numpy as np

from ..core.executor import Executor
from ..core.framework import default_main_program
from ..io import prune_program
from .trainer import _build_feeder

__all__ = ["infer", "Inference"]


class Inference:
    def __init__(self, output_layer, parameters):
        outputs = output_layer if isinstance(output_layer, (list, tuple)) \
            else [output_layer]
        self._outputs = outputs
        self._program = prune_program(default_main_program(),
                                      [v.name for v in outputs])
        self._exe = Executor()

    def infer(self, input, feeding=None, field="value"):
        if feeding is None:
            if isinstance(input, dict):
                feed = input  # already a name -> array feed dict
            else:
                raise ValueError(
                    "v2 infer needs feeding={layer_name: sample_index} "
                    "for tuple-sample input (or pass a feed dict)")
        else:
            feeder = _build_feeder(feeding, len(input[0]))
            feed = feeder.feed(input)
        outs = self._exe.run(self._program, feed=feed,
                             fetch_list=[v.name for v in self._outputs])
        outs = [np.asarray(v) for v in outs]
        return outs[0] if len(outs) == 1 else outs


def infer(output_layer, parameters, input, feeding=None, field="value"):
    return Inference(output_layer, parameters).infer(input,
                                                     feeding=feeding,
                                                     field=field)
