"""paddle.v2-compatible API surface (reference ``python/paddle/v2``):
the legacy keyword-argument layer DSL, SGD trainer, Parameters handle,
datasets/readers/minibatch, and infer — lowered onto the TPU-native
fluid-style engine. SURVEY hard-part 5 named this dual surface; a v2
user ports scripts by changing only the import.

    import paddle_tpu.v2 as paddle
    paddle.init()
    images = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    ...
    trainer = paddle.trainer.SGD(cost, parameters, optimizer)
    trainer.train(paddle.batch(paddle.dataset.mnist.train(), 64), ...)
"""

from .. import dataset  # noqa: F401  (same module names as v2.dataset)
from .. import reader  # noqa: F401
from ..reader import batch  # noqa: F401  (paddle.batch)
from ..utils import image  # noqa: F401
from .. import plot  # noqa: F401
from . import activation  # noqa: F401
from . import data_type  # noqa: F401
from . import event  # noqa: F401
from . import inference  # noqa: F401
from . import layer  # noqa: F401
from . import optimizer  # noqa: F401
from . import parameters  # noqa: F401
from . import pooling  # noqa: F401
from . import trainer  # noqa: F401
from .. import nets as networks  # noqa: F401
from .inference import infer  # noqa: F401
from ..param_attr import ParamAttr as attr  # noqa: F401

__all__ = ["init", "layer", "activation", "pooling", "data_type",
           "event", "trainer", "parameters", "optimizer", "dataset",
           "reader", "batch", "infer", "inference", "networks", "attr",
           "image", "plot"]


def init(use_gpu=False, trainer_count=1, **kwargs):
    """v2 bootstrap (reference paddle.init parsing gflags): devices are
    JAX-managed here; kept for script compatibility."""
    return None
