"""Resilient training supervisor.

``ResilientTrainer`` wraps the :class:`~paddle_tpu.trainer.Trainer`
loop with the recovery behaviors a long-running TPU job needs (the
unhappy paths the reference stack handles across its Go master /
pserver tier, SURVEY §5.3-5.4, reproduced host-side):

* **Non-finite steps** — instead of the assert-and-die
  ``check_nan_inf``, a per-step finite check on the fetched
  loss/metrics applies a configurable policy: ``skip`` (bounded budget
  of identity steps) or ``rollback`` (reload the last intact
  checkpoint, optional LR backoff). Both arm the executor's
  ``nonfinite_guard`` so a poisoned batch cannot corrupt DONATED
  params/optimizer state before the host even sees the NaN.
* **Reader faults** — transient reader exceptions (OSError family by
  default) are retried with exponential backoff and the pass resumes
  at the first unconsumed sample; permanent failures still propagate
  after the retry budget.
* **Preemption** — SIGTERM/SIGINT finish the in-flight step, write a
  final checkpoint whose latest.json carries exact resume metadata,
  and return it from ``train``.
* **Hung steps** — a watchdog thread fires a counter + structured log
  line (optionally aborts the loop) when a step exceeds a deadline.

Every recovery event is visible in the metrics registry
(``paddle_resilience_*``). Deterministic chaos comes from
``resilience.faults`` (armed via the ``fault_injection`` config flag).
"""

import signal
import threading
import time
from contextlib import contextmanager

import numpy as np

from .. import config as _config
from ..observability import metrics as _metrics
from ..trainer import Trainer
from ..utils import log as _log
from . import faults as _faults

__all__ = ["RecoveryPolicy", "ResilientTrainer", "resilient_reader",
           "StepWatchdog", "preemption_guard"]

# Recovery counters: always-on (they fire on rare events, not per step).
_NONFINITE_STEPS = _metrics.REGISTRY.counter(
    "paddle_resilience_nonfinite_steps_total",
    "Steps whose fetched loss/metrics contained NaN/Inf")
_SKIPPED_STEPS = _metrics.REGISTRY.counter(
    "paddle_resilience_skipped_steps_total",
    "Non-finite steps neutralized to identity updates (skip policy)")
_ROLLBACKS = _metrics.REGISTRY.counter(
    "paddle_resilience_rollbacks_total",
    "Non-finite steps answered by reloading the last intact checkpoint")
_READER_RETRIES = _metrics.REGISTRY.counter(
    "paddle_resilience_reader_retries_total",
    "Transient reader failures absorbed by retry-with-backoff")
_WATCHDOG_STALLS = _metrics.REGISTRY.counter(
    "paddle_resilience_watchdog_stalls_total",
    "Steps that exceeded the hung-step watchdog deadline")
_PREEMPTIONS = _metrics.REGISTRY.counter(
    "paddle_resilience_preemptions_total",
    "SIGTERM/SIGINT preemptions handled by a running train loop")


class RecoveryPolicy:
    """Recovery knobs; unset fields default to the config flags
    (``nonfinite_policy``, ``nonfinite_budget``, ``reader_retries``,
    ``step_deadline_sec``)."""

    def __init__(self, nonfinite_policy=None, nonfinite_budget=None,
                 lr_backoff=None, reader_retries=None,
                 reader_backoff=0.05, transient_exceptions=(OSError,),
                 step_deadline_sec=None, watchdog_abort=False,
                 on_hang=None,
                 preempt_signals=(signal.SIGTERM, signal.SIGINT)):
        self.nonfinite_policy = (nonfinite_policy or
                                 _config.get_flag("nonfinite_policy"))
        if self.nonfinite_policy not in ("raise", "skip", "rollback"):
            raise ValueError("nonfinite_policy must be raise|skip|"
                             "rollback, got %r" % (self.nonfinite_policy,))
        self.nonfinite_budget = (
            _config.get_flag("nonfinite_budget")
            if nonfinite_budget is None else nonfinite_budget)
        # rollback only: multiply every learning_rate var by this after
        # each rollback (e.g. 0.5). None = keep LR. With an
        # LRScheduler attached the scheduler re-derives LR per step and
        # the backoff is a no-op — schedule the decay there instead.
        self.lr_backoff = lr_backoff
        self.reader_retries = (
            _config.get_flag("reader_retries")
            if reader_retries is None else reader_retries)
        self.reader_backoff = reader_backoff
        self.transient_exceptions = tuple(transient_exceptions)
        self.step_deadline_sec = (
            _config.get_flag("step_deadline_sec")
            if step_deadline_sec is None else step_deadline_sec)
        self.watchdog_abort = watchdog_abort
        # hang escalation: called (step, elapsed_sec) from the watchdog
        # thread BEFORE the abort — the place to tear down a wedged
        # distributed runtime (e.g. distributed.elastic.collective_abort
        # severs jax.distributed so the abort can actually unwind the
        # loop instead of re-entering the dead collective)
        self.on_hang = on_hang
        self.preempt_signals = tuple(preempt_signals)


def resilient_reader(reader, retries=None, backoff=0.05,
                     transient=(OSError,), on_retry=None):
    """Wrap a reader so transient failures don't kill the pass.

    When iterating the underlying reader raises one of ``transient``,
    the iterator is re-created after an exponential backoff and
    fast-forwarded past the samples already consumed (the reader must
    be re-creatable, the standard reader contract). The SAME failure
    repeating ``retries`` times without progress propagates — permanent
    faults still fail the pass. Each absorbed failure increments
    ``paddle_resilience_reader_retries_total``."""
    if retries is None:
        retries = _config.get_flag("reader_retries")
    transient = tuple(transient)

    def reader_creator():
        consumed = 0
        attempts = 0
        while True:
            pos = 0  # position within THIS iterator
            try:
                # reader() is inside the retried region: a creator that
                # opens its source eagerly can fail transiently too
                it = reader()
                for sample in it:
                    pos += 1
                    if pos <= consumed:
                        continue  # replaying already-delivered samples
                    consumed += 1
                    attempts = 0  # progress resets the budget
                    yield sample
                return
            except transient as e:
                attempts += 1
                _READER_RETRIES.inc()
                if attempts > retries:
                    raise
                delay = backoff * (2 ** (attempts - 1))
                _log.structured("reader_retry", attempt=attempts,
                                retries=retries, consumed=consumed,
                                error=repr(e),
                                backoff_sec=round(delay, 4))
                if on_retry is not None:
                    on_retry(attempts, e)
                time.sleep(delay)
    return reader_creator


def _fault_reader(reader):
    """``reader_error`` chaos hook: raise the armed exception before
    yielding sample ``index`` (only wrapped in when fault injection is
    armed)."""
    def reader_creator():
        for i, sample in enumerate(reader()):
            # default IOError so an exc-less arm() lands in the
            # resilient reader's transient (OSError) set, as documented
            _faults.fire_point("reader_error", i, default_exc=IOError)
            yield sample
    return reader_creator


class StepWatchdog:
    """Background thread that flags steps exceeding a deadline.

    ``step_started``/``step_finished`` bracket each step; when a step
    overruns, the watchdog fires the stall counter plus one structured
    log line (once per step), and with ``abort`` raises
    KeyboardInterrupt in the main thread. The raise lands at the next
    Python bytecode — a hung XLA call itself can't be cancelled from
    Python, so the unwind happens the moment control returns (pair
    with an external supervisor for hard kills). ResilientTrainer
    keeps SIGINT on its default handler while abort is armed, since
    ``interrupt_main`` is delivered as SIGINT."""

    def __init__(self, deadline_sec, abort=False, poll_interval=None,
                 on_hang=None):
        self.deadline = float(deadline_sec)
        self.abort = abort
        # escalation hook, called (step, elapsed) once per overrunning
        # step from the watchdog thread, before the abort fires; errors
        # are logged, never raised — a broken escalation must not kill
        # the watchdog
        self.on_hang = on_hang
        self._poll = poll_interval if poll_interval is not None else \
            min(max(self.deadline / 4.0, 0.005), 1.0)
        self._lock = threading.Lock()
        self._t0 = None
        self._step = None
        self._fired = False
        self._stop_evt = threading.Event()
        self._thread = None

    def start(self):
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="paddle-step-watchdog")
        self._thread.start()
        return self

    def stop(self):
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def step_started(self, step_id):
        with self._lock:
            self._t0 = time.monotonic()
            self._step = step_id
            self._fired = False

    def step_finished(self):
        with self._lock:
            self._t0 = None

    def _run(self):
        while not self._stop_evt.wait(self._poll):
            with self._lock:
                t0, step, fired = self._t0, self._step, self._fired
            if t0 is None or fired:
                continue
            elapsed = time.monotonic() - t0
            if elapsed <= self.deadline:
                continue
            with self._lock:
                if self._fired or self._t0 is not t0:
                    continue
                self._fired = True
            _WATCHDOG_STALLS.inc()
            _log.structured("watchdog_stall", step=step,
                            elapsed_sec=round(elapsed, 3),
                            deadline_sec=self.deadline,
                            abort=self.abort,
                            escalated=self.on_hang is not None)
            if self.on_hang is not None:
                try:
                    self.on_hang(step, elapsed)
                except Exception:  # noqa: BLE001 — watchdog must live
                    _log.logger().warning(
                        "watchdog on_hang escalation failed",
                        exc_info=True)
            if self.abort:
                import _thread
                _thread.interrupt_main()


@contextmanager
def preemption_guard(trainer, signals=(signal.SIGTERM, signal.SIGINT)):
    """Install preemption handlers for the duration of a train loop.

    The handler only sets the trainer's stop flag (signal-safe), so the
    in-flight step completes and the loop writes its final checkpoint
    with resume metadata before exiting. Previous handlers are
    restored on the way out. Outside the main thread (where Python
    forbids signal()) this is a no-op."""
    if not signals or \
            threading.current_thread() is not threading.main_thread():
        yield
        return

    def handler(signum, frame):
        _PREEMPTIONS.inc()
        trainer.request_stop("signal_%d" % signum)
        _log.structured("preemption_signal", signal=int(signum),
                        step=trainer.step_id)

    old = {}
    try:
        for s in signals:
            old[s] = signal.signal(s, handler)
        yield
    finally:
        for s, h in old.items():
            signal.signal(s, h)


class ResilientTrainer(Trainer):
    """Trainer + recovery policy (see module docstring).

    Non-finite detection reads the fetched metrics on the host, which
    forces one device sync per step — with ``async_metrics`` the
    dispatch-ahead pipeline is therefore traded for safety; that is the
    price of *acting* on per-step health.
    """

    def __init__(self, *args, policy=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.policy = policy or RecoveryPolicy()
        self.nonfinite_seen = 0
        self._watchdog = None
        if self.policy.nonfinite_policy != "raise":
            if not _config.get_flag("nonfinite_guard"):
                # skip/rollback are only sound if the donated update is
                # guarded device-side; the flag stays set process-wide
                # (it keys the executor compile cache like amp/precision)
                _config.set_flags(nonfinite_guard=True)
            if _config.get_flag("check_nan_inf"):
                # the legacy assert-and-die flag raises inside the
                # executor BEFORE the policy could run — the guard
                # supersedes it, so disable it rather than let it
                # silently void the configured recovery
                _log.logger().warning(
                    "check_nan_inf disabled: it would abort the step "
                    "before the %r nonfinite policy could act",
                    self.policy.nonfinite_policy)
                _config.set_flags(check_nan_inf=False)

    # -- per-step ------------------------------------------------------------
    def _train_feed(self, feed):
        fault_injection = _config.get_flag("fault_injection")
        if fault_injection:
            feed = _faults.poison_feed(feed, self.step_id)
            # elastic chaos: hard-kill this worker mid-pass (the
            # SIGKILLed-peer shape for subprocess tests)
            _faults.fire_point("worker_kill", self.step_id)
        if self._watchdog is not None:
            self._watchdog.step_started(self.step_id)
        try:
            if fault_injection:
                # wedge INSIDE the watchdog window, like a collective
                # whose peer died — only the on_hang/abort escalation
                # path gets out
                _faults.simulate_collective_hang(self.step_id)
            return super()._train_feed(feed)
        finally:
            if self._watchdog is not None:
                self._watchdog.step_finished()

    def _post_step(self, metrics):
        """Runs inside the base step, before the periodic checkpoint
        trigger — a non-finite step is handled BEFORE it could
        checkpoint itself."""
        if self._watchdog is not None:
            # the timed window is the step itself; recovery work below
            # (a rollback's restore can take arbitrarily long) must not
            # trip the deadline — the outer finally re-calls
            # step_finished(), which is idempotent
            self._watchdog.step_finished()
        if not self._all_finite(metrics):
            return self._handle_nonfinite(metrics)
        # like the reader's retry budget, progress resets it: the
        # budget bounds CONSECUTIVE bad steps (divergence), not
        # isolated glitches over a multi-week job's lifetime
        self.nonfinite_seen = 0
        return metrics

    @staticmethod
    def _all_finite(metrics):
        for v in metrics.values():
            arr = np.asarray(v)
            if np.issubdtype(arr.dtype, np.floating) and \
                    not np.isfinite(arr).all():
                return False
        return True

    def _handle_nonfinite(self, metrics):
        self.nonfinite_seen += 1
        _NONFINITE_STEPS.inc()
        policy = self.policy.nonfinite_policy
        budget = self.policy.nonfinite_budget
        if policy == "raise":
            raise FloatingPointError(
                "non-finite loss/metrics at step %d (policy=raise)"
                % self.step_id)
        if self.nonfinite_seen > budget:
            raise FloatingPointError(
                "non-finite budget exhausted: %d consecutive bad steps "
                "> budget %d (policy=%s) — training is diverging, not "
                "glitching" % (self.nonfinite_seen, budget, policy))
        if policy == "skip":
            # nonfinite_guard already turned the update into identity
            # on device; the step is recorded as consumed-but-skipped
            _SKIPPED_STEPS.inc()
            _log.structured("nonfinite_skip", step=self.step_id,
                            seen=self.nonfinite_seen, budget=budget)
            out = dict(metrics)
            out["skipped_nonfinite"] = True
            return out
        # rollback — capture the LIVE learning rates first: they carry
        # every previous backoff, while the LR var inside the restored
        # checkpoint may predate them (persistable state). Backing off
        # from the live value makes consecutive rollbacks compound
        # (0.1 -> 0.05 -> 0.025) instead of bouncing off the
        # checkpointed LR.
        pre_lrs = self._current_lrs() if self.policy.lr_backoff else None
        step = self.restore_checkpoint()
        if step is None:
            raise FloatingPointError(
                "non-finite step %d and no checkpoint to roll back to "
                "(set checkpoint_dir / checkpoint_every_n_steps)"
                % self.step_id)
        _ROLLBACKS.inc()
        if pre_lrs:
            self._set_lrs({n: v * self.policy.lr_backoff
                           for n, v in pre_lrs.items()})
        _log.structured("nonfinite_rollback", restored_step=step,
                        seen=self.nonfinite_seen, budget=budget,
                        lr_backoff=self.policy.lr_backoff)
        out = dict(metrics)
        out["rolled_back_to"] = step
        return out

    def _current_lrs(self):
        from ..core.scope import global_scope
        scope = global_scope()
        return {name: np.asarray(scope.find_var(name))
                for name in self.main_program.global_block().vars
                if name.startswith("learning_rate")
                and scope.has_var(name)}

    def _set_lrs(self, values):
        from ..core.scope import global_scope
        scope = global_scope()
        for name, v in values.items():
            scope.set_var(name, v)

    # -- pass loop -----------------------------------------------------------
    def train(self, reader, num_passes=1, event_handler=None,
              prefetch=8, staging=True):
        wrapped = reader
        if _config.get_flag("fault_injection"):
            wrapped = _fault_reader(wrapped)
        if self.policy.reader_retries:
            wrapped = resilient_reader(
                wrapped, retries=self.policy.reader_retries,
                backoff=self.policy.reader_backoff,
                transient=self.policy.transient_exceptions)
        if self.policy.step_deadline_sec:
            self._watchdog = StepWatchdog(
                self.policy.step_deadline_sec,
                abort=self.policy.watchdog_abort,
                on_hang=self.policy.on_hang).start()
        sigs = self.policy.preempt_signals
        if self.policy.watchdog_abort:
            # the abort path delivers interrupt_main() as SIGINT; if the
            # preemption guard owned SIGINT it would downgrade the
            # abort to a stop-flag a hung step never checks — leave
            # SIGINT on its default handler so KeyboardInterrupt
            # actually unwinds the loop
            sigs = tuple(s for s in sigs if s != signal.SIGINT)
        try:
            with preemption_guard(self, sigs):
                return super().train(wrapped, num_passes=num_passes,
                                     event_handler=event_handler,
                                     prefetch=prefetch, staging=staging)
        finally:
            if self._watchdog is not None:
                self._watchdog.stop()
                self._watchdog = None
