"""Fault-tolerant training: supervisor, recovery policies, chaos tools.

At production scale restarts and partial failures are the steady state
(ROADMAP north star), so recovery is designed and tested here rather
than accidental:

* ``supervisor``  — :class:`ResilientTrainer`: non-finite-loss policies
  (skip-with-budget / rollback-with-LR-backoff, safe under donated
  state via the executor's ``nonfinite_guard``), retrying reader
  wrapper, SIGTERM/SIGINT preemption that checkpoints and exits with
  resume metadata, and a hung-step watchdog.
* ``faults``      — deterministic fault-injection registry driving the
  chaos tests (``tests/test_resilience.py``) and the headless probe
  (``tools/chaos_probe.py``).
* crash-safe checkpoints live in ``paddle_tpu.io``: temp-dir +
  atomic-rename publish, sha256 manifests, verified load with fallback
  to the newest intact checkpoint.

Every recovery event is a counter in the observability registry
(``paddle_resilience_*`` / ``paddle_checkpoint_*``).

NOTE: only ``faults`` is imported eagerly — ``supervisor`` pulls in the
trainer stack, and ``io`` imports this package for its chaos hook, so
the heavy import is deferred via module ``__getattr__``.
"""

from . import faults  # noqa: F401  (light: config + logging only)

_SUPERVISOR_EXPORTS = ("ResilientTrainer", "RecoveryPolicy",
                       "resilient_reader", "StepWatchdog",
                       "preemption_guard")

__all__ = ["faults"] + list(_SUPERVISOR_EXPORTS)


def __getattr__(name):
    if name in _SUPERVISOR_EXPORTS:
        from . import supervisor
        return getattr(supervisor, name)
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
