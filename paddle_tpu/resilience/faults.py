"""Deterministic fault injection for chaos testing.

The registry maps a *site* (a string naming a hook point compiled into
the production code path) to armed :class:`FaultSpec` s. Hook sites call
``fire_point(site, index)`` — a no-op unless the ``fault_injection``
config flag is on AND a spec armed for that site matches ``index``
(step / batch number). Matching specs execute their action exactly
``times`` times, so chaos tests are reproducible: "NaN loss at step 3",
"reader IOError at batch 5", "SIGKILL during checkpoint write at step
6" — no sleeps-and-hope.

Built-in hook sites:

============================  =============================================
site                          where / what
============================  =============================================
``nan_loss``                  ResilientTrainer poisons the feed (first
                              float array -> NaN) before the step, so the
                              NaN propagates through the REAL computation
``reader_error``              the reader raises the armed exception
                              (default IOError — inside the resilient
                              reader's transient set) before yielding
                              sample ``index``
``checkpoint_crash``          io.save_checkpoint, after the checkpoint
                              data is fully written into the temp dir but
                              BEFORE the atomic rename publishes it
``master_kill``               ElasticDataDispatcher.reader, once per task
                              lease — arm with a callback that kills (and
                              optionally restarts) the master
``serving_replica_fail``      ServingEngine._execute, before the replica
                              lock — ``index`` is the REPLICA number, so
                              ``at=1`` fails only replica 1 (the breaker/
                              failover chaos shape)
``serving_replica_slow``      ServingEngine._execute, inside the replica
                              lock just before the device run — arm with
                              ``action="callback"`` sleeping past the
                              engine timeout to simulate a wedged device
``serving_overload``          MicroBatcher.submit admission — ``index``
                              is the submit sequence number; default
                              exception ServingOverloadError (counted as
                              a shed)
``worker_kill``               ResilientTrainer, before the step — arm with
                              ``action="kill"`` at a step to SIGKILL one
                              worker of an elastic multi-host run mid-pass
                              (the peer-death chaos shape)
``heartbeat_drop``            distributed.elastic MembershipHeartbeat —
                              ``index`` is the beat number; a firing spec
                              SWALLOWS the beat (no exception), so
                              ``times=K`` simulates K beats of network
                              partition and forces a master-declared death
                              of a live process
``collective_hang``           ResilientTrainer, before the step — the step
                              blocks like an all-reduce whose peer died
                              (interruptible sleep loop; a ``callback``
                              spec runs instead if armed that way). Only
                              the watchdog's abort escalation gets out —
                              the bounded-hang proof for step_deadline_sec
``cache_corrupt``             PersistentCompileCache.load, before the
                              manifest read — a raising spec is treated
                              exactly like on-disk corruption: the entry
                              is quarantined and the caller recompiles
``swap_bad_artifact``         ServingEngine.swap_weights validation gate —
                              the push is rejected (SwapRejectedError)
                              with the prior weights untouched
``swap_canary_fail``          ServingEngine.swap_weights, before the
                              canary execution — simulates a push whose
                              weights fail on real traffic shapes
``generation_step_fail``      GenerationScheduler decode dispatch, before
                              the session's step() — ``index`` is the
                              SESSION number. Arm with ``times=None`` for
                              persistent mode (the session is broken until
                              disarmed): the replay-failover / session-
                              rebuild chaos shape
``generation_admit_fail``     GenerationScheduler, before a prompt's
                              prefill admission — indexed by session; a
                              raising spec makes admission (including a
                              replay re-admission) fail there
``generation_session_wedge``  GenerationScheduler, inside the (possibly
                              worker-bounded) step dispatch — arm with
                              ``action="callback"`` sleeping past
                              ``generation_step_timeout_ms`` to simulate a
                              wedged decode step; only the step-timeout
                              escalation gets the dispatcher out
``fleet_member_kill``         EngineWorker token-stream loop — ``index``
                              is the per-request streamed-token count;
                              arm with ``action="kill"`` (in the WORKER
                              process) to SIGKILL the member
                              mid-generation: the router re-drives its
                              in-flight journals on a peer
``fleet_network_partition``   both ends of the fleet wire: the router
                              fires it before dispatching to a member
                              (``index`` = member id, default exception
                              ConnectionError) and the worker's heartbeat
                              loop SWALLOWS beats under the same site —
                              one arm per process simulates the matching
                              direction of a partition
``fleet_slow_member``         EngineWorker, before serving a request —
                              ``index`` is the member id; arm with
                              ``action="callback"`` sleeping past the
                              router's ``call_timeout`` to simulate a
                              wedged member (hang = instant breaker open)
``fleet_spawn_fail``          FleetAutoscaler launch thread, before the
                              spawn callable runs — ``index`` is the
                              would-be member id; a raising spec IS the
                              spawn that died before REGistering: the
                              pending entry resolves to a failure and is
                              charged to the spawn-failure budget
``fleet_spawn_slow``          FleetAutoscaler launch thread, after the
                              spawn callable returned — arm with
                              ``action="callback"`` sleeping past
                              ``autoscale_spawn_timeout_ms``: the launch
                              wedges, the monitor tick's sweep (never
                              blocked by it) kills the handle and
                              charges the budget at the deadline
``decode_draft_mismatch``     GenerationSession speculative verify —
                              ``index`` is the slot; one firing forces
                              that slot's round to accept ZERO draft
                              tokens (worst-case draft disagreement: the
                              rollback path runs, the output must not
                              change)
``decode_constraint_dead_end``GenerationScheduler, after each landed
                              token of a CONSTRAINED request — ``index``
                              is the slot; a firing forces the dead-end
                              verdict, so the request resolves with the
                              typed :class:`ConstraintDeadEnd` client
                              error (never a hang, never a replay)
``model_page_in_fail``        EngineWorker page_in handler, before any
                              weight lands — ``index`` is the model id;
                              a raising spec IS a torn/refused artifact:
                              the member keeps its resident set and the
                              router charges the page-in to the
                              autoscaler's spawn-failure budget
``model_page_in_slow``        EngineWorker page_in handler — arm with
                              ``action="callback"`` sleeping past
                              ``model_page_timeout_ms`` (``index`` =
                              model id): the router times the page-in
                              out, charges the budget, and retries on a
                              peer
``model_evict_race``          FleetRouter eviction pressure, between
                              victim selection and the page_out send —
                              ``index`` is the victim model id; a
                              raising spec aborts the eviction round
                              (the victim stays resident), the window a
                              late pin would otherwise race
============================  =============================================

Actions: ``"raise"`` (raise ``exc``, default :class:`InjectedFault`),
``"kill"`` (``os.kill(os.getpid(), SIGKILL)`` — the real
process-death simulation for subprocess chaos tests), or
``"callback"`` (run an arbitrary callable, e.g. kill a helper daemon).
"""

import os
import signal
import threading
import time

from .. import config as _config
from ..utils import log as _log

__all__ = ["InjectedFault", "arm", "disarm", "armed", "should_fire",
           "fire_point", "poison_feed", "simulate_collective_hang"]


class InjectedFault(Exception):
    """Raised by an armed ``action="raise"`` fault."""


class FaultSpec:
    __slots__ = ("site", "at", "times", "action", "exc", "callback")

    def __init__(self, site, at=None, times=1, action="raise", exc=None,
                 callback=None):
        if action not in ("raise", "kill", "callback"):
            raise ValueError("unknown fault action %r" % (action,))
        if action == "callback" and callback is None:
            raise ValueError("action='callback' needs a callback")
        self.site = site
        self.at = at          # index (step/batch) to fire at; None = any
        # remaining firings; None = persistent (fires on every match
        # until disarmed — the "session is broken, not glitching"
        # chaos shape)
        self.times = times
        self.action = action
        self.exc = exc
        self.callback = callback


_LOCK = threading.Lock()
_ARMED = {}  # site -> [FaultSpec]


def arm(site, at=None, times=1, action="raise", exc=None, callback=None):
    """Arm a fault (also flips the ``fault_injection`` config flag on).
    ``times=None`` arms PERSISTENT mode: the fault fires on every
    match until ``disarm()`` — "this session/replica is broken", as
    opposed to the counted "it glitched N times"."""
    spec = FaultSpec(site, at=at, times=times, action=action, exc=exc,
                     callback=callback)
    with _LOCK:
        _ARMED.setdefault(site, []).append(spec)
    if not _config.get_flag("fault_injection"):
        _config.set_flags(fault_injection=True)
    return spec


def disarm(site=None):
    """Drop armed faults for ``site`` (or all of them). When nothing
    remains armed, the ``fault_injection`` master switch is cleared too
    — hook sites go back to one flag check, and ResilientTrainer stops
    wrapping readers in the fault hook."""
    with _LOCK:
        if site is None:
            _ARMED.clear()
        else:
            _ARMED.pop(site, None)
        empty = not any(_ARMED.values())
    if empty and _config.get_flag("fault_injection"):
        _config.set_flags(fault_injection=False)


def armed(site=None):
    with _LOCK:
        if site is None:
            return {s: list(v) for s, v in _ARMED.items()}
        return list(_ARMED.get(site, ()))


def should_fire(site, index=None):
    """The matching armed spec (consuming one firing), or None.

    Cheap when disarmed: one config-flag check, no lock."""
    if not _config.get_flag("fault_injection"):
        return None
    with _LOCK:
        for spec in _ARMED.get(site, ()):
            if spec.times is not None and spec.times <= 0:
                continue
            if spec.at is not None and index is not None \
                    and spec.at != index:
                continue
            if spec.times is not None:
                spec.times -= 1
            return spec
    return None


def fire_point(site, index=None, default_exc=None):
    """Hook-site entry: execute the armed action for ``site`` if any.

    Returns the spec when a non-raising action fired (so the caller can
    branch), None when nothing fired. ``default_exc`` lets a hook site
    pick the exception class raised when the armed spec didn't name
    one (e.g. the reader site defaults to IOError so the fault lands
    in the resilient reader's transient set)."""
    spec = should_fire(site, index)
    if spec is None:
        return None
    # stamp the injected fault onto the request being served (chaos
    # probes correlate "which request ate which fault" off the span
    # tree); a thread-local read + None check, nothing when tracing
    # is off
    from ..observability import request_trace as _rtrace
    ctx = _rtrace.current()
    if ctx is not None:
        _rtrace.event(ctx, "faultInjected", site=site, index=index,
                      action=spec.action)
    _log.structured("fault_injected", site=site, index=index,
                    action=spec.action,
                    trace_id=None if ctx is None else ctx.trace_id)
    if spec.action == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    if spec.action == "callback":
        spec.callback()
        return spec
    if spec.exc is not None:
        raise spec.exc
    raise (default_exc or InjectedFault)(
        "injected fault at %s[%s]" % (site, index))


def simulate_collective_hang(step, max_sec=600.0):
    """``collective_hang`` hook: when armed for ``step``, block like a
    collective whose peer was SIGKILLed — an interruptible sleep loop
    that only an asynchronous unwind (the step watchdog's
    ``interrupt_main`` abort, delivered as KeyboardInterrupt) escapes.
    A ``callback`` spec runs the callback instead. ``max_sec`` is a
    backstop so an unwatched test can't wedge CI forever; a REAL hung
    XLA call has no such mercy, which is the point of the escalation
    path this site exists to prove."""
    spec = should_fire("collective_hang", step)
    if spec is None:
        return
    _log.structured("fault_injected", site="collective_hang",
                    index=step, action=spec.action)
    if spec.action == "callback":
        spec.callback()
        return
    deadline = None if max_sec is None else \
        (time.monotonic() + max_sec)
    while deadline is None or time.monotonic() < deadline:
        time.sleep(0.05)
    raise InjectedFault(
        "collective_hang at step %s outlived its %.0fs backstop — "
        "no watchdog abort arrived" % (step, max_sec))


def poison_feed(feed, step):
    """``nan_loss`` hook: overwrite the first float feed array with NaN
    (in a copy) when armed for ``step``, so a genuinely non-finite loss
    flows through the unmodified train computation. Packed batches
    (core/ingest.py) are poisoned in place of their first float slot's
    byte region, so the fused single-copy path stays on its own code
    path under chaos testing."""
    import numpy as np
    if should_fire("nan_loss", step) is None:
        return feed
    _log.structured("fault_injected", site="nan_loss", index=step,
                    action="poison")
    from ..core.ingest import PackedBatch
    if isinstance(feed, PackedBatch):
        for slot in feed.layout:
            dt = np.dtype(slot.dtype)
            if not np.issubdtype(dt, np.floating):
                continue
            import jax.numpy as jnp
            nan_bytes = np.frombuffer(
                np.full(slot.nbytes // dt.itemsize, np.nan, dt)
                .tobytes(), np.uint8)
            buf = jnp.asarray(feed.buffer).at[
                :, slot.offset:slot.offset + slot.nbytes].set(
                jnp.asarray(nan_bytes))
            poisoned = PackedBatch(buf, feed.layout, feed.shards,
                                   feed.shard_nbytes, feed.batch_size)
            poisoned.transfer_done = True
            return poisoned
        return feed
    out = dict(feed)
    for name, v in out.items():
        arr = np.asarray(v)
        if np.issubdtype(arr.dtype, np.floating):
            out[name] = np.full_like(arr, np.nan)
            break
    return out
