"""Beam-search layer surface: step op, decode op, and the sub-block
decoder builder composable with any model.

Reference parity: fluid exposed ``beam_search`` / ``beam_search_decode``
as layer-callable ops inside a While loop, and the legacy engine offered
config-driven generation (``RecurrentGradientMachine::beamSearch``,
``trainer_config_helpers`` beam_search/generated_input). Here the
engine-level surface is ``BeamSearchDecoder``: build the per-token step as
a sub-block (any layers: GRU, attention, transformer), and the
``dynamic_beam_search`` op runs the whole search as one fused scan
(ops/beam_search_ops.py).
"""

import contextlib

from ..core import unique_name
from ..layer_helper import LayerHelper
from .control_flow import _block_external_reads

__all__ = ["beam_search_step", "beam_search_decode", "BeamSearchDecoder"]


def beam_search_step(pre_scores, logits, done, eos_id=1,
                     is_log_prob=False, **kwargs):
    """One beam expansion (reference beam_search_op contract): top-k over
    beam*vocab per source, ended beams frozen. pre_scores/done: [B,K];
    logits: [B*K,V]. Returns (scores, parent, token, done_out)."""
    helper = LayerHelper("beam_search", **kwargs)
    scores = helper.create_tmp_variable("float32", stop_gradient=True)
    parent = helper.create_tmp_variable("int32", stop_gradient=True)
    token = helper.create_tmp_variable("int32", stop_gradient=True)
    done_out = helper.create_tmp_variable("bool", stop_gradient=True)
    helper.append_op(
        type="beam_search",
        inputs={"PreScores": [pre_scores.name], "Logits": [logits.name],
                "Done": [done.name]},
        outputs={"Scores": [scores.name], "Parent": [parent.name],
                 "Token": [token.name], "DoneOut": [done_out.name]},
        attrs={"eos_id": eos_id, "is_log_prob": is_log_prob})
    return scores, parent, token, done_out


def beam_search_decode(step_tokens, step_parents, final_scores, eos_id=1,
                       length_penalty="avg", **kwargs):
    """Backtrack recorded per-step (token, parent) arrays [L,B,K] into
    ranked sequences (reference beam_search_decode_op). Returns
    (ids [B,K,L], length [B,K], scores [B,K]) sorted best-first."""
    helper = LayerHelper("beam_search_decode", **kwargs)
    ids = helper.create_tmp_variable("int32", stop_gradient=True)
    length = helper.create_tmp_variable("int32", stop_gradient=True)
    scores = helper.create_tmp_variable("float32", stop_gradient=True)
    helper.append_op(
        type="beam_search_decode",
        inputs={"StepTokens": [step_tokens.name],
                "StepParents": [step_parents.name],
                "FinalScores": [final_scores.name]},
        outputs={"Ids": [ids.name], "Length": [length.name],
                 "Scores": [scores.name]},
        attrs={"eos_id": eos_id, "length_penalty": length_penalty})
    return ids, length, scores


class BeamSearchDecoder:
    """Beam search over a user-built step block (any decoder model).

    Usage::

        bs = BeamSearchDecoder(beam_size=4, max_len=32, bos_id=0, eos_id=1)
        with bs.step():
            tok = bs.token()              # [N] int32, N = batch*beam
            h_prev = bs.state(h0)         # [B,H] tiled to [N,H]
            emb = layers.embedding(tok, ...)
            h = <any layers>(emb, h_prev, ...)
            bs.update_state(h_prev, h)
            bs.set_logits(layers.fc(h, V))
        ids, lengths, scores = bs()       # best beam per source

    Optional step inputs: ``bs.position()`` — [1] int32 current step;
    ``bs.history()`` — [N, max_len] int32 tokens so far (EOS-padded,
    maintained by the op; for transformer-style full-context steps).
    States never passed to ``update_state`` are carried unchanged
    (encoder outputs etc. — tiled per beam once).
    """

    def __init__(self, beam_size=4, max_len=32, bos_id=0, eos_id=1,
                 length_penalty="avg", name=None, main_program=None,
                 decode="beam", sample_seed=0, temperature=1.0,
                 top_k=0, top_p=1.0):
        if decode not in ("beam", "sample"):
            raise ValueError("decode must be 'beam' or 'sample', got "
                             "%r" % (decode,))
        if decode == "sample" and beam_size != 1:
            raise ValueError("decode='sample' needs beam_size=1 (one "
                             "sampled trajectory per source)")
        self.helper = LayerHelper("beam_search_decoder", name=name,
                                  main_program=main_program)
        self.program = self.helper.main_program
        self.decode = decode
        self.sample_seed = int(sample_seed)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.beam_size = beam_size
        self.max_len = max_len
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.length_penalty = length_penalty
        self._token = None
        self._pos = None
        self._hist = None
        self._states = []    # [sub prev var, outer init var, updated var]
        self._logits = None
        self._outs = None

    @contextlib.contextmanager
    def step(self):
        self.parent_block = self.program.current_block()
        self.sub_block = self.program.create_block()
        yield
        self.program.rollback()
        self._complete()

    def token(self):
        if self._token is None:
            self._token = self.sub_block.create_var(
                name=unique_name.generate("beam.token"), shape=(-1,),
                dtype="int32")
        return self._token

    def position(self):
        if self._pos is None:
            self._pos = self.sub_block.create_var(
                name=unique_name.generate("beam.pos"), shape=(1,),
                dtype="int32")
        return self._pos

    def history(self):
        if self._hist is None:
            self._hist = self.sub_block.create_var(
                name=unique_name.generate("beam.hist"),
                shape=(-1, self.max_len), dtype="int32")
        return self._hist

    def state(self, init):
        prev = self.sub_block.create_var(
            name=unique_name.generate("beam.state"), shape=init.shape,
            dtype=init.dtype)
        self._states.append([prev, init, None])
        return prev

    def update_state(self, prev, new):
        for entry in self._states:
            if entry[0] is prev:
                entry[2] = new
                return
        raise ValueError("update_state: %r is not a state" % prev.name)

    def set_logits(self, logits):
        self._logits = logits

    def _complete(self):
        if self._token is None:
            raise ValueError("step block never called token()")
        if self._logits is None:
            raise ValueError("step block never called set_logits()")
        internal = {self._token.name}
        if self._pos is not None:
            internal.add(self._pos.name)
        if self._hist is not None:
            internal.add(self._hist.name)
        internal |= {s[0].name for s in self._states}
        captured = [n for n in _block_external_reads(self.sub_block)
                    if n not in internal and self.parent_block.has_var(n)]
        K, L = self.beam_size, self.max_len
        init0 = self._states[0][1] if self._states else None
        batch = init0.shape[0] if init0 is not None and init0.shape else -1
        mk = self.parent_block.create_var
        ids = mk(name=unique_name.generate("beam.ids"),
                 shape=(batch, K, L), dtype="int32", stop_gradient=True)
        length = mk(name=unique_name.generate("beam.len"),
                    shape=(batch, K), dtype="int32", stop_gradient=True)
        scores = mk(name=unique_name.generate("beam.scores"),
                    shape=(batch, K), dtype="float32", stop_gradient=True)
        if not self._states:
            raise ValueError("beam search needs at least one state() to "
                             "size the batch")
        self.parent_block.append_op(
            type="dynamic_beam_search",
            inputs={"InitStates": [s[1].name for s in self._states],
                    "Captured": captured},
            outputs={"Ids": [ids.name], "Length": [length.name],
                     "Scores": [scores.name]},
            attrs={"sub_block": self.sub_block.idx,
                   "token_var": self._token.name,
                   "pos_var": self._pos.name if self._pos else None,
                   "hist_var": self._hist.name if self._hist else None,
                   "logits_var": self._logits.name,
                   "state_vars": [(s[0].name,
                                   (s[2] or s[0]).name) for s in
                                  self._states],
                   "captured_vars": captured,
                   "beam_size": K, "max_len": L,
                   "bos_id": self.bos_id, "eos_id": self.eos_id,
                   "length_penalty": self.length_penalty,
                   "decode": self.decode,
                   "sample_seed": self.sample_seed,
                   "temperature": self.temperature,
                   "top_k": self.top_k, "top_p": self.top_p},
            infer_shape=False)
        self._outs = (ids, length, scores)

    def __call__(self, return_all_beams=False):
        """Returns (ids, length, scores): best beam ([B,L],[B],[B]) or all
        beams sorted best-first ([B,K,L],[B,K],[B,K])."""
        ids, length, scores = self._outs
        if return_all_beams:
            return ids, length, scores
        # beams are sorted best-first: beam 0 slice is the argmax beam
        from .tensor import slice as _slice, reshape as _reshape
        best_ids = _reshape(_slice(ids, [1], [0], [1]),
                            [-1, self.max_len])
        best_len = _reshape(_slice(length, [1], [0], [1]), [-1])
        best_scores = _reshape(_slice(scores, [1], [0], [1]), [-1])
        return best_ids, best_len, best_scores
