"""Sequence layers over PADDED batches — the TPU-native replacement for the
reference's LoD machinery (SURVEY §5.7, B.1).

Design: a "sequence batch" is (data[batch, time, ...], length[batch]) —
static shapes for XLA, explicit lengths instead of LoD offsets. The
capability preserved is the same (no quadratic padding waste comes from
bucketing in the reader, see paddle_tpu.reader); the ops mask padding so
results match the reference's variable-length semantics exactly.

Covers: sequence_pool (+first/last step), sequence_softmax, sequence_expand,
sequence_conv, dynamic_lstm, dynamic_gru (lax.scan over time — the analog of
the fused hl_cuda_lstm kernels / sequence2batch scheduling).
"""

import numpy as np

from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = ["sequence_mask", "sequence_pool", "sequence_first_step",
           "sequence_last_step", "sequence_softmax", "sequence_expand",
           "sequence_conv", "dynamic_lstm", "dynamic_gru", "gru_unit",
           "lstm_unit", "sequence_reverse", "sequence_erase_pad",
           "sequence_slice", "sequence_concat", "nested_sequence_mask",
           "nested_sequence_pool", "sub_seq", "sub_nested_seq",
           "nested_flatten", "nested_unflatten", "sequence_reshape",
           "lod_reset", "max_sequence_len", "sequence_concat_packed"]


def sequence_mask(length, maxlen, dtype="float32", **kwargs):
    helper = LayerHelper("sequence_mask", **kwargs)
    out = helper.create_tmp_variable(dtype, stop_gradient=True)
    helper.append_op(type="sequence_mask",
                     inputs={"Length": [length.name]},
                     outputs={"Out": [out.name]},
                     attrs={"maxlen": maxlen, "dtype": dtype})
    return out


def sequence_pool(input, pool_type, length=None, **kwargs):
    """Pool over time with padding masked (reference sequence_pool_op:
    average/sum/sqrt/max/last/first)."""
    helper = LayerHelper("sequence_pool", **kwargs)
    inputs = {"X": [input.name]}
    if length is not None:
        inputs["Length"] = [length.name]
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="sequence_pool", inputs=inputs,
                     outputs={"Out": [out.name]},
                     attrs={"pool_type": pool_type})
    return out


def sequence_first_step(input, length=None, **kwargs):
    return sequence_pool(input, "first", length, **kwargs)


def sequence_last_step(input, length=None, **kwargs):
    return sequence_pool(input, "last", length, **kwargs)


def sequence_softmax(input, length=None, **kwargs):
    helper = LayerHelper("sequence_softmax", **kwargs)
    inputs = {"X": [input.name]}
    if length is not None:
        inputs["Length"] = [length.name]
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="sequence_softmax", inputs=inputs,
                     outputs={"Out": [out.name]})
    return out


def sequence_expand(x, y, y_length=None, **kwargs):
    """Expand per-sequence rows of ``x`` [b, d] across ``y``'s time axis
    (padded analog of sequence_expand_op). With ``y_length`` the repeat
    count varies per row (reference per-sequence lod(y) repeats): rows
    past a row's length are zeroed."""
    helper = LayerHelper("sequence_expand", **kwargs)
    inputs = {"X": [x.name], "Y": [y.name]}
    if y_length is not None:
        inputs["Length"] = [y_length.name]
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="sequence_expand", inputs=inputs,
                     outputs={"Out": [out.name]})
    return out


def sequence_reverse(x, length=None, **kwargs):
    helper = LayerHelper("sequence_reverse", **kwargs)
    inputs = {"X": [x.name]}
    if length is not None:
        inputs["Length"] = [length.name]
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="sequence_reverse", inputs=inputs,
                     outputs={"Out": [out.name]})
    return out


def sequence_erase_pad(x, length, tokens, **kwargs):
    """Remove tokens from padded int sequences, repacking left
    (reference sequence_erase_op)."""
    helper = LayerHelper("sequence_erase", **kwargs)
    out = helper.create_tmp_variable(x.dtype)
    new_len = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="sequence_erase",
                     inputs={"X": [x.name], "Length": [length.name]},
                     outputs={"Out": [out.name], "OutLength": [new_len.name]},
                     attrs={"tokens": list(tokens)})
    return out, new_len


def sequence_slice(input, offset, length_attr, **kwargs):
    """Slice [offset, offset+length) along time (sequence_slice_op)."""
    helper = LayerHelper("sequence_slice", **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="slice", inputs={"Input": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"axes": [1], "starts": [offset],
                            "ends": [offset + length_attr]})
    return out


def sequence_concat(inputs, **kwargs):
    """Concatenate along time (sequence_concat_op on padded batches)."""
    helper = LayerHelper("sequence_concat", **kwargs)
    out = helper.create_tmp_variable(inputs[0].dtype)
    helper.append_op(type="concat",
                     inputs={"X": [v.name for v in inputs]},
                     outputs={"Out": [out.name]}, attrs={"axis": 1})
    return out


def sequence_conv(input, num_filters, filter_size=3, param_attr=None,
                  bias_attr=None, act=None, **kwargs):
    """Context-window projection over time (reference sequence_conv_op /
    ContextProjection): same-padding 1-D conv over [batch, time, dim]."""
    helper = LayerHelper("sequence_conv", act=act, **kwargs)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr,
                                shape=[filter_size * dim, num_filters],
                                dtype=input.dtype)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="sequence_conv",
                     inputs={"X": [input.name], "Filter": [w.name]},
                     outputs={"Out": [out.name]},
                     attrs={"contextLength": filter_size,
                            "contextStart": -(filter_size // 2)})
    if bias_attr is not False:
        out = helper.append_bias_op(out, ParamAttr.to_attr(bias_attr),
                                    dim_start=2)
    return helper.append_activation(out)


def dynamic_lstm(input, size, length=None, param_attr=None, bias_attr=None,
                 use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", h0=None, c0=None, **kwargs):
    """LSTM over padded [batch, time, 4*hidden] projected input (reference
    dynamic_lstm / LstmLayer / hl_cuda_lstm fused kernels). The time loop is
    a lax.scan — XLA compiles it to a fused while loop on TPU; padded steps
    carry state through unchanged (the analog of the shrinking-batch
    scheduling in sequence2batch, SURVEY B.2).

    ``input`` must already be the gate projection x·W (4*size wide), as in
    the reference where dynamic_lstm consumes a fc output.
    """
    helper = LayerHelper("dynamic_lstm", **kwargs)
    w = helper.create_parameter(param_attr, shape=[size, 4 * size],
                                dtype=input.dtype)
    inputs = {"Input": [input.name], "Weight": [w.name]}
    if bias_attr is not False:
        nbias = 7 * size if use_peepholes else 4 * size
        bias = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                       shape=[1, nbias],
                                       dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [bias.name]
    if length is not None:
        inputs["Length"] = [length.name]
    if h0 is not None:
        inputs["H0"] = [h0.name]
    if c0 is not None:
        inputs["C0"] = [c0.name]
    hidden = helper.create_tmp_variable(input.dtype)
    cell = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="dynamic_lstm", inputs=inputs,
                     outputs={"Hidden": [hidden.name], "Cell": [cell.name]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation})
    return hidden, cell


def dynamic_gru(input, size, length=None, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h0=None, **kwargs):
    """GRU over padded [batch, time, 3*hidden] projected input (reference
    dynamic_gru / GatedRecurrentLayer / hl_gpu_gru)."""
    helper = LayerHelper("dynamic_gru", **kwargs)
    w = helper.create_parameter(param_attr, shape=[size, 3 * size],
                                dtype=input.dtype)
    inputs = {"Input": [input.name], "Weight": [w.name]}
    if bias_attr is not False:
        bias = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                       shape=[1, 3 * size],
                                       dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [bias.name]
    if length is not None:
        inputs["Length"] = [length.name]
    if h0 is not None:
        inputs["H0"] = [h0.name]
    hidden = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="dynamic_gru", inputs=inputs,
                     outputs={"Hidden": [hidden.name]},
                     attrs={"is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "candidate_activation": candidate_activation})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", **kwargs):
    """Single GRU step (reference gru_unit_op) for explicit RNN loops."""
    helper = LayerHelper("gru_unit", **kwargs)
    w = helper.create_parameter(param_attr, shape=[size, 3 * size],
                                dtype=input.dtype)
    bias = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                   shape=[1, 3 * size], dtype=input.dtype,
                                   is_bias=True)
    new_hidden = helper.create_tmp_variable(input.dtype)
    gate = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    reset_h = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    helper.append_op(type="gru_unit",
                     inputs={"Input": [input.name],
                             "HiddenPrev": [hidden.name],
                             "Weight": [w.name], "Bias": [bias.name]},
                     outputs={"Hidden": [new_hidden.name],
                              "Gate": [gate.name],
                              "ResetHiddenPrev": [reset_h.name]},
                     attrs={"activation": activation,
                            "gate_activation": gate_activation})
    return new_hidden, gate, reset_h


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, **kwargs):
    """Single LSTM step (reference lstm_unit_op): fc([x, h]) -> gates."""
    from . import nn as _nn
    from . import tensor as _tensor
    size = cell_t_prev.shape[-1]
    concat_in = _tensor.concat([x_t, hidden_t_prev], axis=1, **kwargs)
    fc_out = _nn.fc(concat_in, 4 * size, param_attr=param_attr,
                    bias_attr=bias_attr, **kwargs)
    helper = LayerHelper("lstm_unit", **kwargs)
    h = helper.create_tmp_variable(x_t.dtype)
    c = helper.create_tmp_variable(x_t.dtype)
    helper.append_op(type="lstm_unit",
                     inputs={"X": [fc_out.name], "C_prev": [cell_t_prev.name]},
                     outputs={"H": [h.name], "C": [c.name]},
                     attrs={"forget_bias": forget_bias})
    return h, c


# -- nested (2-level) sequences ---------------------------------------------
# Convention (ops/nested_ops.py; reference Argument.h:84-90
# subSequenceStartPositions, RecurrentGradientMachine.cpp:380-383):
# (data[B, S, T, ...], seq_len[B], sub_len[B, S]).

def nested_sequence_mask(seq_len, sub_len, max_sub, maxlen, **kwargs):
    """Returns (outer[B,S], inner[B,S,T]) float masks."""
    helper = LayerHelper("nested_sequence_mask", **kwargs)
    outer = helper.create_tmp_variable("float32", stop_gradient=True)
    inner = helper.create_tmp_variable("float32", stop_gradient=True)
    helper.append_op(type="nested_sequence_mask",
                     inputs={"SeqLen": [seq_len.name],
                             "SubLen": [sub_len.name]},
                     outputs={"Outer": [outer.name],
                              "Inner": [inner.name]},
                     attrs={"max_sub": max_sub, "maxlen": maxlen})
    return outer, inner


def nested_sequence_pool(input, sub_len, pool_type="average", **kwargs):
    """Pool the innermost level: [B,S,T,...] -> [B,S,...] (reference
    sequence_pool over a 2-level LoD). Chain with sequence_pool(.,
    length=seq_len) for the outer level."""
    helper = LayerHelper("nested_sequence_pool", **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="nested_sequence_pool",
                     inputs={"X": [input.name], "SubLen": [sub_len.name]},
                     outputs={"Out": [out.name]},
                     attrs={"pool_type": pool_type})
    return out


def sub_seq(input, offset, size, max_size, **kwargs):
    """Per-sequence window slice (reference SubSequenceLayer): returns
    ([B, max_size, ...] left-packed, new_length[B])."""
    helper = LayerHelper("sub_seq", **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    out_len = helper.create_tmp_variable("int32", stop_gradient=True)
    helper.append_op(type="sub_seq",
                     inputs={"X": [input.name], "Offset": [offset.name],
                             "Size": [size.name]},
                     outputs={"Out": [out.name], "OutLen": [out_len.name]},
                     attrs={"max_size": max_size})
    return out, out_len


def sub_nested_seq(input, sub_len, selected, **kwargs):
    """Select sub-sequences by per-sequence indices (reference
    SubNestedSequenceLayer): ([B,S,T,...], [B,S], [B,K]) ->
    ([B,K,T,...], [B,K]); negative index -> empty sub-sequence."""
    helper = LayerHelper("sub_nested_seq", **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    out_sub = helper.create_tmp_variable("int32", stop_gradient=True)
    helper.append_op(type="sub_nested_seq",
                     inputs={"X": [input.name], "SubLen": [sub_len.name],
                             "Selected": [selected.name]},
                     outputs={"Out": [out.name],
                              "OutSubLen": [out_sub.name]})
    return out, out_sub


def nested_flatten(input, sub_len, **kwargs):
    """[B,S,T,...] -> ([B*S,T,...], [B*S]) — run any level-1 sequence op
    (dynamic_lstm/gru, sequence_pool...) over the sub-sequences, then
    nested_unflatten back. This is the TPU-native nested recurrent
    group: the reference clones per-frame sub-networks with scatter/
    gather agents (RecurrentGradientMachine.cpp:380-383,462-529); here
    the inner level is just a bigger batch."""
    from . import tensor as _tensor
    shape = list(input.shape)
    flat = _tensor.reshape(input, [-1] + shape[2:], **kwargs)
    flat_len = _tensor.reshape(sub_len, [-1], **kwargs)
    return flat, flat_len


def nested_unflatten(input, batch, max_sub, **kwargs):
    """[B*S, ...] -> [B, S, ...] (inverse of nested_flatten's batch
    collapse, after the inner-level op)."""
    from . import tensor as _tensor
    shape = list(input.shape)
    return _tensor.reshape(input, [batch, max_sub] + shape[1:], **kwargs)


def sequence_reshape(input, new_dim, length=None, **kwargs):
    """Change per-timestep width, scaling lengths (reference
    sequence_reshape_op). Returns (out, new_length|None).
    Caller contract (as in the reference's per-sequence enforce):
    every valid length must satisfy (length * D) % new_dim == 0."""
    helper = LayerHelper("sequence_reshape", **kwargs)
    inputs = {"X": [input.name]}
    out = helper.create_tmp_variable(input.dtype)
    outputs = {"Out": [out.name]}
    new_len = None
    if length is not None:
        inputs["Length"] = [length.name]
        new_len = helper.create_tmp_variable(length.dtype,
                                             stop_gradient=True)
        outputs["OutLength"] = [new_len.name]
    # infer_shape off: with a dynamic time axis the T*D divisibility
    # check is only meaningful at trace time against the concrete feed
    helper.append_op(type="sequence_reshape", inputs=inputs,
                     outputs=outputs, attrs={"new_dim": new_dim},
                     infer_shape=False)
    return out, new_len


def lod_reset(x, new_length, original_length=None, **kwargs):
    """Re-declare a batch's sequence lengths (reference lod_reset_op).
    Returns (x_passthrough, clipped_length). Pass ``original_length``
    to also clip against the CURRENT valid lengths — without it, a
    grown length exposes padding rows as data (the padded-batch hazard
    the dense-rows reference does not have)."""
    helper = LayerHelper("lod_reset", **kwargs)
    out = helper.create_tmp_variable(x.dtype)
    out_len = helper.create_tmp_variable(new_length.dtype,
                                         stop_gradient=True)
    inputs = {"X": [x.name], "Length": [new_length.name]}
    if original_length is not None:
        inputs["OrigLength"] = [original_length.name]
    helper.append_op(type="lod_reset", inputs=inputs,
                     outputs={"Out": [out.name],
                              "OutLength": [out_len.name]})
    return out, out_len


def max_sequence_len(length, **kwargs):
    """Max sequence length in the batch (max_sequence_len_op)."""
    helper = LayerHelper("max_sequence_len", **kwargs)
    out = helper.create_tmp_variable(length.dtype, stop_gradient=True)
    helper.append_op(type="max_sequence_len",
                     inputs={"Length": [length.name]},
                     outputs={"Out": [out.name]})
    return out


def sequence_concat_packed(a, b, len_a, len_b, **kwargs):
    """Per-sample packed time concat: (out [B, Ta+Tb, ...], len [B])."""
    helper = LayerHelper("sequence_concat_packed", **kwargs)
    out = helper.create_tmp_variable(a.dtype)
    out_len = helper.create_tmp_variable("int32", stop_gradient=True)
    helper.append_op(type="sequence_concat_packed",
                     inputs={"A": [a.name], "B": [b.name],
                             "LenA": [len_a.name], "LenB": [len_b.name]},
                     outputs={"Out": [out.name], "OutLen": [out_len.name]})
    return out, out_len
