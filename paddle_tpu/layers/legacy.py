"""Legacy gserver layer tail — the last reference layers without
fluid-style analogs (VERDICT r3 Missing #2).

Real ops (ops/legacy_tail_ops.py): bilinear_interp, selective_fc,
data_norm, mdlstm, lambda_cost, cross_entropy_over_beam. The rest are
compositions over existing ops — the TPU-native shape of the
reference's thin C++ layers (InterpolationLayer.cpp, LinearCombLayer,
SlopeInterceptLayer, RepeatLayer(=FeatureMapExpand sibling),
RotateLayer, OuterProdLayer, PowerLayer, TransLayer, L2DistanceLayer,
SumToOneNormLayer, RowL2NormLayer, EosIdCheckLayer, gated_unit /
cross_entropy_with_selfnorm / multi_binary_label CE DSL composites in
``trainer_config_helpers/layers.py``)."""

import numpy as np

from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from .nn import _single, fc
from . import control_flow as _cf
from . import nn as _nn
from . import ops as _opsmod
from . import tensor as _tensormod


class _Flat:
    """Flat layer-namespace resolver (the composition bodies read like
    the public ``layers.*`` surface regardless of which submodule a
    function lives in)."""

    def __getattr__(self, name):
        for m in (_nn, _opsmod, _tensormod, _cf):
            if hasattr(m, name):
                return getattr(m, name)
        raise AttributeError(name)


_ops = _tensor = _Flat()

__all__ = [
    "bilinear_interp", "selective_fc", "data_norm", "mdlstm",
    "lambda_cost", "cross_entropy_over_beam", "interpolation",
    "linear_comb", "slope_intercept", "repeat", "rotate", "out_prod",
    "gated_unit", "power", "trans", "l2_distance", "sum_to_one_norm",
    "row_l2_norm", "eos", "cross_entropy_with_selfnorm",
    "multi_binary_label_cross_entropy", "sum_cost",
    "cos_sim_vec_mat", "featmap_expand", "convex_comb",
]


def bilinear_interp(input, out_h, out_w, name=None, **kwargs):
    """Corner-aligned bilinear resize of NCHW maps (reference
    BilinearInterpLayer.cpp)."""
    helper = LayerHelper("bilinear_interp", name=name, **kwargs)
    return _single(helper, "bilinear_interp", {"X": [input.name]},
                   {"out_h": int(out_h), "out_w": int(out_w)})


def selective_fc(input, size, select=None, param_attr=None,
                 bias_attr=None, act=None, name=None, **kwargs):
    """FC computing only the selected output columns (reference
    SelectiveFullyConnectedLayer.cpp). ``select`` is an int tensor
    [B, K] of output-column ids (-1 = padding -> 0); without it this is
    the reference's full_output path (plain fc)."""
    helper = LayerHelper("selective_fc", act=act, name=name, **kwargs)
    w = helper.create_parameter(param_attr,
                                shape=[input.shape[-1], size],
                                dtype=input.dtype)
    inputs = {"X": [input.name], "W": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                    shape=[size], dtype=input.dtype,
                                    is_bias=True)
        inputs["Bias"] = [b.name]
    if select is not None:
        inputs["Sel"] = [select.name]
    return _single(helper, "selective_fc", inputs, {}, act=True)


def data_norm(input, mode="z-score", stats=None, name=None, **kwargs):
    """Per-feature data normalization (reference DataNormLayer.cpp):
    z-score | min-max | decimal-scaling. ``stats`` supplies the
    normalization statistics as numpy arrays keyed by mean/std/min/max;
    they become non-trainable persistable vars (the analog of the
    reference's static data-meta parameter)."""
    from ..initializer import NumpyArrayInitializer
    helper = LayerHelper("data_norm", name=name, **kwargs)
    stats = stats or {}
    d = input.shape[-1]
    needed = {"z-score": ("mean", "std"), "min-max": ("min", "max"),
              "decimal-scaling": ("max",)}[mode]
    inputs = {"X": [input.name]}
    for key in needed:
        arr = np.asarray(stats[key], dtype="float32")
        if arr.shape != (d,):
            raise ValueError("data_norm stat %r must have shape (%d,)"
                             % (key, d))
        var = helper.create_parameter(
            ParamAttr(name="%s_%s" % (helper.name, key),
                      initializer=NumpyArrayInitializer(arr),
                      trainable=False),
            shape=[d], dtype="float32")
        inputs[key.capitalize()] = [var.name]
    return _single(helper, "data_norm", inputs, {"mode": mode})


def mdlstm(input, num_blocks, directions=(True, True), param_attr=None,
           bias_attr=None, name=None, **kwargs):
    """2-D multi-dimensional LSTM over an NHWC grid (reference
    MDLstmLayer.cpp). input: [B, H, W, C]. Returns [B, H, W,
    num_blocks]. directions[d]=False scans that axis backwards."""
    helper = LayerHelper("mdlstm", name=name, **kwargs)
    c_in = input.shape[-1]
    nb = num_blocks
    wx = helper.create_parameter(param_attr, shape=[c_in, 5 * nb],
                                 dtype=input.dtype)
    # recurrent weight / peephole get auto-generated distinct names
    # (a named param_attr only pins the x-projection weight)
    wh = helper.create_parameter(None, shape=[nb, 5 * nb],
                                 dtype=input.dtype)
    bias = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                   shape=[5 * nb], dtype=input.dtype,
                                   is_bias=True)
    peep = helper.create_parameter(None, shape=[4 * nb],
                                   dtype=input.dtype, is_bias=True)
    # x projection: one matmul over the whole grid (MXU-friendly),
    # the recurrence consumes precomputed gate pre-activations
    flat = _tensor.reshape(input, [-1, c_in])
    gx = fc(flat, 5 * nb, param_attr=ParamAttr(name=wx.name),
            bias_attr=(ParamAttr(name=bias.name)
                       if bias is not None else False),
            name=helper.name + "_gx")
    b_, h_, w_, _ = input.shape
    gx = _tensor.reshape(gx, [-1, h_, w_, 5 * nb])
    return _single(helper, "mdlstm",
                   {"GatesX": [gx.name], "WeightH": [wh.name],
                    "Peephole": [peep.name]},
                   {"directions": tuple(bool(d) for d in directions)})


def lambda_cost(input, score, length=None, NDCG_num=5,
                max_sort_size=-1, name=None, **kwargs):
    """LambdaRank cost (reference CostLayer.cpp LambdaCost /
    lambda_cost DSL). input: model scores [B, L]; score: true relevance
    [B, L]; length: valid lengths [B] (padded-batch LoD analog).
    max_sort_size: accepted for signature parity; this implementation
    always full-sorts (the reference's partial sort is a CPU cost
    optimization with identical results when >= list size)."""
    helper = LayerHelper("lambda_cost", name=name, **kwargs)
    inputs = {"X": [input.name], "Score": [score.name]}
    if length is None:
        # dynamic batch: [-1] leading dim -> batch-size-like fill
        length = _tensor.fill_constant_batch_size_like(
            input, [-1], "int64", input.shape[-1])
    inputs["Length"] = [length.name]
    return _single(helper, "lambda_cost", inputs,
                   {"NDCG_num": int(NDCG_num),
                    "max_sort_size": int(max_sort_size)})


def cross_entropy_over_beam(beams, name=None, **kwargs):
    """Globally-normalized CE over beam expansions (reference
    CrossEntropyOverBeam.cpp / cross_entropy_over_beam DSL). ``beams``:
    list of (scores [B,S], ids [B,R,W] int, gold [B] int) triples, one
    per expansion step — the padded analogs of the reference's
    BeamInput nested-LoD triples. Returns cost [B, 1]."""
    helper = LayerHelper("cross_entropy_over_beam", name=name, **kwargs)
    scores, ids, gold = zip(*beams)
    return _single(helper, "cross_entropy_over_beam",
                   {"Scores": [s.name for s in scores],
                    "Ids": [i.name for i in ids],
                    "Gold": [g.name for g in gold]}, {})


# ---- compositions ----------------------------------------------------

def interpolation(input, input2, weight, name=None):
    """y = w*x1 + (1-w)*x2, per-row scalar weight [B, 1] (reference
    InterpolationLayer.cpp / interpolation_layer DSL)."""
    return _ops.elementwise_add(
        _ops.elementwise_mul(input, weight),
        _ops.elementwise_mul(input2,
                             _ops.scale(weight, scale=-1.0, bias=1.0)))


def linear_comb(weights, vectors, size, name=None):
    """z = x^T Y per sample: weights [B, M], vectors [B, M*size]
    (reference LinearCombLayer / linear_comb_layer DSL)."""
    m = weights.shape[-1]
    y = _tensor.reshape(vectors, [-1, m, size])
    w = _tensor.reshape(weights, [-1, 1, m])
    return _tensor.reshape(_ops.matmul(w, y), [-1, size])


def slope_intercept(input, slope=1.0, intercept=0.0, name=None):
    """y = slope*x + intercept (reference SlopeInterceptLayer)."""
    return _ops.scale(input, scale=slope, bias=intercept)


def repeat(input, num_repeats, as_row_vector=True, name=None):
    """Repeat each row's features (reference RepeatLayer):
    as_row_vector: y = [x1..xn, x1..xn, ...]; else y = [x1,x1,..,xn,xn]
    (each element repeated)."""
    d = input.shape[-1]
    if as_row_vector:
        return _tensor.concat([input] * num_repeats, axis=-1)
    x3 = _tensor.reshape(input, [-1, d, 1])
    tiled = _tensor.concat([x3] * num_repeats, axis=-1)
    return _tensor.reshape(tiled, [-1, d * num_repeats])


def rotate(input, height, width, name=None):
    """Rotate each sample's [C, H, W] maps 90 deg clockwise:
    y(j, i) = x(M-i-1, j) (reference RotateLayer / rotate_layer DSL,
    flattened rows [B, C*H*W])."""
    c = (input.shape[-1] // (height * width)
         if len(input.shape) == 2 else input.shape[1])
    x = _tensor.reshape(input, [-1, c, height, width])
    # clockwise 90deg = flip rows then transpose H<->W
    out = _ops.transpose(_ops.flip(x, axis=2), perm=[0, 1, 3, 2])
    return _tensor.reshape(out, [-1, c * height * width])


def out_prod(input1, input2, name=None):
    """Per-sample outer product: [B,M] x [B,N] -> [B, M*N] (reference
    OuterProdLayer / out_prod_layer DSL)."""
    m, n = input1.shape[-1], input2.shape[-1]
    a = _tensor.reshape(input1, [-1, m, 1])
    b = _tensor.reshape(input2, [-1, 1, n])
    return _tensor.reshape(_ops.matmul(a, b), [-1, m * n])


def gated_unit(input, size, act=None, gate_param_attr=None,
               gate_bias_attr=None, inproj_param_attr=None,
               inproj_bias_attr=None, name=None):
    """y = act(X.W + b) * sigmoid(X.V + c) (reference gated_unit_layer
    DSL; Dauphin et al. gated linear unit)."""
    proj = fc(input, size, act=act, param_attr=inproj_param_attr,
              bias_attr=inproj_bias_attr)
    gate = fc(input, size, act="sigmoid", param_attr=gate_param_attr,
              bias_attr=gate_bias_attr)
    return _ops.elementwise_mul(proj, gate)


def power(input, weight, name=None):
    """y = x^w with per-row scalar exponent [B, 1] (reference
    PowerLayer / power_layer DSL)."""
    return _ops.elementwise_pow(input, weight)


def trans(input, name=None):
    """Transpose the whole [B, D] data matrix to [D, B] (reference
    TransLayer, used for weight sharing tricks)."""
    return _ops.transpose(input, perm=[1, 0])


def l2_distance(x, y, name=None):
    """Per-row euclidean distance [B, 1] (reference L2DistanceLayer)."""
    d = _ops.elementwise_sub(x, y)
    s = _ops.reduce_sum(_ops.square(d), dim=-1, keep_dim=True)
    return _ops.sqrt(s)


def sum_to_one_norm(input, name=None):
    """Row-normalize to sum 1 (reference SumToOneNormLayer)."""
    s = _ops.reduce_sum(input, dim=-1, keep_dim=True)
    return _ops.elementwise_div(input, s)


def row_l2_norm(input, name=None):
    """Row-normalize to unit L2 norm (reference RowL2NormLayer)."""
    return _ops.l2_normalize(input, axis=-1)


def eos(input, eos_id, name=None):
    """1.0 where the max-id equals eos_id (reference EosIdCheckLayer):
    input is a probability/score row; output [B, 1] indicator."""
    from . import nn as _nn
    _, idx = _nn.topk(input, k=1)
    return _ops.cast(_ops.equal(
        idx, _tensor.fill_constant([1], "int64", eos_id)), "float32")


def cross_entropy_with_selfnorm(input, label, softmax_selfnorm_alpha=0.1,
                                name=None):
    """CE + alpha * log(Z)^2 self-normalization (reference
    cross_entropy_with_selfnorm DSL): input is softmax output; the
    self-norm term pushes each row's partition toward 1."""
    ce = _ops.cross_entropy(input, label)
    z = _ops.reduce_sum(input, dim=-1, keep_dim=True)
    logz = _ops.log(z)
    return _ops.elementwise_add(
        ce, _ops.scale(_ops.square(logz),
                       scale=float(softmax_selfnorm_alpha)))


def multi_binary_label_cross_entropy(input, label, name=None):
    """Sum of per-class binary CE with probability input and multi-hot
    labels (reference MultiBinaryLabelCrossEntropy)."""
    eps = 1e-8
    one = _ops.scale(input, scale=-1.0, bias=1.0)
    loss = _ops.elementwise_add(
        _ops.elementwise_mul(label,
                             _ops.scale(_ops.log(
                                 _ops.scale(input, bias=eps)), -1.0)),
        _ops.elementwise_mul(_ops.scale(label, scale=-1.0, bias=1.0),
                             _ops.scale(_ops.log(
                                 _ops.scale(one, bias=eps)), -1.0)))
    return _ops.reduce_sum(loss, dim=-1, keep_dim=True)


def sum_cost(input, name=None):
    """Sum of the input as a scalar cost (reference SumCostLayer)."""
    return _ops.reduce_sum(input)


def cos_sim_vec_mat(vec, mat, scale=1.0, name=None):
    """cos_vm (reference CosSimVecMatLayer, 'used in NEURAL TURING
    MACHINE'): out[b, i] = scale * cos(vec[b], mat[b, i*D:(i+1)*D]).
    vec: [B, D]; mat: [B, M*D] -> [B, M]."""
    d = vec.shape[-1]
    m3 = _tensor.reshape(mat, [-1, mat.shape[-1] // d, d])
    v3 = _tensor.reshape(vec, [-1, 1, d])
    dots = _ops.reduce_sum(_ops.elementwise_mul(m3, v3), dim=-1)
    vn = _ops.sqrt(_ops.reduce_sum(_ops.square(vec), dim=-1,
                                   keep_dim=True))
    mn = _ops.sqrt(_ops.reduce_sum(_ops.square(m3), dim=-1))
    eps = 1e-12  # the cos_sim op's epsilon (ops/math_ops.py) — one
    # convention for every cosine path
    cos = _ops.elementwise_div(
        dots, _ops.scale(_ops.elementwise_mul(mn, vn), bias=eps))
    return _ops.scale(cos, scale=float(scale)) if scale != 1.0 else cos


def featmap_expand(input, num_filters, as_row_vector=True, name=None):
    """FeatureMapExpandLayer: tile the feature row num_filters times —
    y.row[i] = x.row[i mod width] (identical math to repeat with
    as_row_vector=True; registered under the reference's name)."""
    return repeat(input, num_filters, as_row_vector=as_row_vector)


convex_comb = linear_comb  # reference REGISTER_LAYER(convex_comb, ...)
