"""Layers API — flat namespace like reference ``fluid.layers``
(``python/paddle/v2/fluid/layers/``)."""

from .io import *        # noqa: F401,F403
from .nn import *        # noqa: F401,F403
from .tensor import *    # noqa: F401,F403
from .ops import *       # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .beam_search import *  # noqa: F401,F403
from .legacy import *    # noqa: F401,F403

from . import (io, nn, tensor, ops, sequence, control_flow, detection,  # noqa
               beam_search, legacy)

__all__ = (io.__all__ + nn.__all__ + tensor.__all__ + ops.__all__ +
           sequence.__all__ + control_flow.__all__ + detection.__all__ +
           beam_search.__all__ + legacy.__all__)
