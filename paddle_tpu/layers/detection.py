"""Detection layers — the SSD family (reference roi_pool_op,
PriorBox.cpp, MultiBoxLossLayer.cpp, detection_output_op; SURVEY
A.1/A.2). Ops in ops/detection_ops.py; the mAP metric is the host-side
DetectionMAP evaluator (evaluator.py), matching the reference's
CPU-evaluator architecture (DetectionMAPEvaluator.cpp)."""

from ..layer_helper import LayerHelper

__all__ = ["roi_pool", "prior_box", "box_coder", "multibox_loss",
           "detection_output"]


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, **kwargs):
    helper = LayerHelper("roi_pool", **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    argmax = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="roi_pool",
                     inputs={"X": [input.name], "ROIs": [rois.name]},
                     outputs={"Out": [out.name], "Argmax": [argmax.name]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variances=(0.1, 0.1, 0.2, 0.2), flip=True, clip=True,
              step_w=0.0, step_h=0.0, offset=0.5, **kwargs):
    """SSD anchors for a feature map (PriorBox.cpp:95-150). Returns
    (boxes [H,W,P,4], variances [H,W,P,4]) in normalized corners."""
    helper = LayerHelper("prior_box", **kwargs)
    boxes = helper.create_tmp_variable("float32", stop_gradient=True)
    var = helper.create_tmp_variable("float32", stop_gradient=True)
    helper.append_op(type="prior_box",
                     inputs={"Input": [input.name],
                             "Image": [image.name]},
                     outputs={"Boxes": [boxes.name],
                              "Variances": [var.name]},
                     attrs={"min_sizes": list(min_sizes),
                            "max_sizes": list(max_sizes or []),
                            "aspect_ratios": list(aspect_ratios or []),
                            "variances": list(variances), "flip": flip,
                            "clip": clip, "step_w": step_w,
                            "step_h": step_h, "offset": offset})
    return boxes, var


def box_coder(prior_box_var, prior_box, target_box,
              code_type="decode_center_size", **kwargs):
    helper = LayerHelper("box_coder", **kwargs)
    out = helper.create_tmp_variable(target_box.dtype)
    helper.append_op(type="box_coder",
                     inputs={"PriorBox": [prior_box.name],
                             "PriorBoxVar": [prior_box_var.name],
                             "TargetBox": [target_box.name]},
                     outputs={"OutputBox": [out.name]},
                     attrs={"code_type": code_type})
    return out


def multibox_loss(loc, conf, prior_boxes, prior_variances, gt_box,
                  gt_label, gt_count, overlap_threshold=0.5,
                  neg_pos_ratio=3.0, background_label=0, **kwargs):
    """SSD training loss (MultiBoxLossLayer.cpp). loc [N,P,4], conf
    logits [N,P,C], padded GT (boxes [N,G,4], labels [N,G],
    count [N]). Returns (loss, loc_loss, conf_loss) scalars."""
    helper = LayerHelper("multibox_loss", **kwargs)
    loss = helper.create_tmp_variable("float32")
    ll = helper.create_tmp_variable("float32")
    cl = helper.create_tmp_variable("float32")
    helper.append_op(
        type="multibox_loss",
        inputs={"Loc": [loc.name], "Conf": [conf.name],
                "PriorBox": [prior_boxes.name],
                "PriorBoxVar": [prior_variances.name],
                "GtBox": [gt_box.name], "GtLabel": [gt_label.name],
                "GtCount": [gt_count.name]},
        outputs={"Loss": [loss.name], "LocLoss": [ll.name],
                 "ConfLoss": [cl.name]},
        attrs={"overlap_threshold": overlap_threshold,
               "neg_pos_ratio": neg_pos_ratio,
               "background_label": background_label})
    return loss, ll, cl


def detection_output(loc, scores, prior_boxes, prior_variances,
                     background_label=0, confidence_threshold=0.01,
                     nms_threshold=0.45, nms_top_k=64, keep_top_k=16,
                     **kwargs):
    """Decode + per-class NMS + top-k (detection_output_op.h). scores
    are post-softmax probabilities [N,P,C]. Output [N, keep_top_k, 6]:
    (label, score, xmin, ymin, xmax, ymax), label -1 = empty row."""
    helper = LayerHelper("detection_output", **kwargs)
    out = helper.create_tmp_variable("float32", stop_gradient=True)
    helper.append_op(
        type="detection_output",
        inputs={"Loc": [loc.name], "Scores": [scores.name],
                "PriorBox": [prior_boxes.name],
                "PriorBoxVar": [prior_variances.name]},
        outputs={"Out": [out.name]},
        attrs={"background_label": background_label,
               "confidence_threshold": confidence_threshold,
               "nms_threshold": nms_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k})
    return out
