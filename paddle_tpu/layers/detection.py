"""Detection layers (reference roi_pool_op, detection_output, prior_box,
multibox_loss — SURVEY A.1/A.2). Round 1: roi_pool; the SSD family follows.
"""

from ..layer_helper import LayerHelper

__all__ = ["roi_pool"]


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, **kwargs):
    helper = LayerHelper("roi_pool", **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    argmax = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="roi_pool",
                     inputs={"X": [input.name], "ROIs": [rois.name]},
                     outputs={"Out": [out.name], "Argmax": [argmax.name]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out
