"""Transformer layers: multi-head attention, encoder layer, positional
embedding. (Capability upgrade over the reference's additive-attention NMT
demo; ring_axis enables sequence parallelism over the mesh.)"""

import numpy as np

from ..layer_helper import LayerHelper
from ..initializer import NormalInitializer
from . import nn as _nn
from . import ops as _ops

__all__ = ["multi_head_attention", "multi_head_attention_cached",
           "transformer_encoder_layer", "positional_encoding",
           "positional_encoding_window"]


def multi_head_attention(queries, keys, values, d_model, num_heads,
                         causal=False, key_length=None, ring_axis=None,
                         param_attr=None, name=None, **kwargs):
    """Full MHA with input/output projections. queries/keys/values:
    [B, T, D]. ``ring_axis``: mesh axis name for ring (sequence-parallel)
    attention."""
    helper = LayerHelper("multi_head_attention", name=name, **kwargs)
    # default param names carry tp-able suffixes: .qkv.* weights are
    # column-parallel ([D, D] sharded on dim 1), .o.* row-parallel —
    # see models.transformer.transformer_tp_rules
    from ..core import unique_name
    prefix = name or unique_name.generate("mha")

    def attr(suffix):
        return param_attr if param_attr is not None else \
            "%s.%s.w" % (prefix, suffix)
    q = _nn.fc(queries, d_model, num_flatten_dims=2, bias_attr=False,
               param_attr=attr("qkv_q"), **kwargs)
    k = _nn.fc(keys, d_model, num_flatten_dims=2, bias_attr=False,
               param_attr=attr("qkv_k"), **kwargs)
    v = _nn.fc(values, d_model, num_flatten_dims=2, bias_attr=False,
               param_attr=attr("qkv_v"), **kwargs)
    inputs = {"Q": [q.name], "K": [k.name], "V": [v.name]}
    if key_length is not None:
        inputs["KeyLength"] = [key_length.name]
    ctx_out = helper.create_tmp_variable(queries.dtype)
    helper.append_op(type="multihead_attention", inputs=inputs,
                     outputs={"Out": [ctx_out.name]},
                     attrs={"num_heads": num_heads, "causal": causal,
                            "ring_axis": ring_axis})
    return _nn.fc(ctx_out, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=attr("o"), **kwargs)


def multi_head_attention_cached(x, cache, d_model, num_heads,
                                key_length=None, param_attr=None,
                                name=None, **kwargs):
    """KV-cached MHA for autoregressive generation — the SAME
    projections (and parameter names) as :func:`multi_head_attention`,
    with K/V routed through persistable per-layer cache variables
    (ops/generation_ops.py) instead of being recomputed from history.

    ``cache``: dict with ``k``/``v`` ([slots, cache_len, d_model]
    persistable Variables) and ``mode``:

    * ``"prefill"`` — x is one prompt [1, P, D]; the prompt's K/V rows
      are written into cache slot ``cache["slot"]`` at positions
      [0, P) and attention runs causally within the prompt window
      (``key_length`` masks right-padding).
    * ``"decode"`` — x is one token per slot [S, 1, D]; K/V rows are
      appended at per-slot positions ``cache["pos"]`` and the single
      query attends cache rows [0, pos] per slot (its own row
      included).

    With ``cache["layout"] == "paged"`` the k/v Variables are
    [num_blocks, block_size, d_model] block POOLS and the ops route
    through a block table (``cache["table"]``; ops/generation_ops.py
    paged variants): prefill becomes a suffix-window prefill — x is
    the UNSHARED tail of the prompt, ``cache["hist"]`` rows are
    already cached (shared prefix blocks) and the window attends the
    cached prefix plus itself causally — and decode gathers each
    slot's K/V through its table row. Same masking/softmax contracts
    as the dense layout; token parity is a test invariant.

    Because the q/k/v/o parameter names match the uncached layer
    (same ``unique_name`` sequence), programs built under the same
    ``unique_name.guard()`` discipline share weights through the scope
    — the cached decode path serves a scope trained by the standard
    transformer program."""
    helper = LayerHelper("multi_head_attention", name=name, **kwargs)
    from ..core import unique_name
    prefix = name or unique_name.generate("mha")

    def attr(suffix):
        return param_attr if param_attr is not None else \
            "%s.%s.w" % (prefix, suffix)
    q = _nn.fc(x, d_model, num_flatten_dims=2, bias_attr=False,
               param_attr=attr("qkv_q"), **kwargs)
    k = _nn.fc(x, d_model, num_flatten_dims=2, bias_attr=False,
               param_attr=attr("qkv_k"), **kwargs)
    v = _nn.fc(x, d_model, num_flatten_dims=2, bias_attr=False,
               param_attr=attr("qkv_v"), **kwargs)
    ck, cv = cache["k"], cache["v"]
    ctx_out = helper.create_tmp_variable(x.dtype)
    if cache.get("layout") == "paged":
        table = cache["table"]
        if cache["mode"] == "prefill":
            hist = cache["hist"]
            # window rows land at positions [hist, hist+key_length)
            # through the block table; padding rows drop
            for cvar, proj in ((ck, k), (cv, v)):
                helper.append_op(type="kv_cache_write_paged",
                                 inputs={"Cache": [cvar.name],
                                         "New": [proj.name],
                                         "Table": [table.name],
                                         "Hist": [hist.name],
                                         "Len": [key_length.name]},
                                 outputs={"Out": [cvar.name]})
            helper.append_op(type="multihead_attention_prefill_paged",
                             inputs={"Q": [q.name], "CacheK": [ck.name],
                                     "CacheV": [cv.name],
                                     "Table": [table.name],
                                     "Hist": [hist.name],
                                     "Len": [key_length.name]},
                             outputs={"Out": [ctx_out.name]},
                             attrs={"num_heads": num_heads})
        elif cache["mode"] == "decode":
            pos = cache["pos"]
            for cvar, proj in ((ck, k), (cv, v)):
                helper.append_op(type="kv_cache_append_paged",
                                 inputs={"Cache": [cvar.name],
                                         "New": [proj.name],
                                         "Pos": [pos.name],
                                         "Table": [table.name]},
                                 outputs={"Out": [cvar.name]})
            helper.append_op(type="multihead_attention_decode_paged",
                             inputs={"Q": [q.name], "CacheK": [ck.name],
                                     "CacheV": [cv.name],
                                     "Pos": [pos.name],
                                     "Table": [table.name]},
                             outputs={"Out": [ctx_out.name]},
                             attrs={"num_heads": num_heads})
        else:
            raise ValueError("cache mode must be 'prefill' or "
                             "'decode', got %r" % (cache["mode"],))
        return _nn.fc(ctx_out, d_model, num_flatten_dims=2,
                      bias_attr=False, param_attr=attr("o"), **kwargs)
    if cache["mode"] == "prefill":
        slot = cache["slot"]
        # cache writes alias the cache variable name: the executor
        # marks it written (state_rw) and donates it, so the update is
        # in place in HBM
        helper.append_op(type="kv_cache_write_slot",
                         inputs={"Cache": [ck.name], "New": [k.name],
                                 "Slot": [slot.name]},
                         outputs={"Out": [ck.name]})
        helper.append_op(type="kv_cache_write_slot",
                         inputs={"Cache": [cv.name], "New": [v.name],
                                 "Slot": [slot.name]},
                         outputs={"Out": [cv.name]})
        inputs = {"Q": [q.name], "K": [k.name], "V": [v.name]}
        if key_length is not None:
            inputs["KeyLength"] = [key_length.name]
        helper.append_op(type="multihead_attention", inputs=inputs,
                         outputs={"Out": [ctx_out.name]},
                         attrs={"num_heads": num_heads, "causal": True,
                                "ring_axis": None})
    elif cache["mode"] == "decode":
        pos = cache["pos"]
        helper.append_op(type="kv_cache_append",
                         inputs={"Cache": [ck.name], "New": [k.name],
                                 "Pos": [pos.name]},
                         outputs={"Out": [ck.name]})
        helper.append_op(type="kv_cache_append",
                         inputs={"Cache": [cv.name], "New": [v.name],
                                 "Pos": [pos.name]},
                         outputs={"Out": [cv.name]})
        helper.append_op(type="multihead_attention_decode",
                         inputs={"Q": [q.name], "CacheK": [ck.name],
                                 "CacheV": [cv.name],
                                 "Pos": [pos.name]},
                         outputs={"Out": [ctx_out.name]},
                         attrs={"num_heads": num_heads})
    else:
        raise ValueError("cache mode must be 'prefill' or 'decode', "
                         "got %r" % (cache["mode"],))
    return _nn.fc(ctx_out, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=attr("o"), **kwargs)


def transformer_encoder_layer(x, d_model, num_heads, d_ff, causal=False,
                              key_length=None, ring_axis=None,
                              dropout_prob=0.0, is_test=False, name=None,
                              cache=None, **kwargs):
    """Pre-norm transformer block: x + MHA(LN(x)); x + FFN(LN(x)).
    ``cache`` (see :func:`multi_head_attention_cached`) swaps the
    attention for the KV-cached prefill/decode variant; every
    parameter name is unchanged."""
    ln1 = _nn.layer_norm(x, begin_norm_axis=2, **kwargs)
    if cache is not None:
        if ring_axis:
            raise ValueError(
                "cache= is incompatible with ring_axis (the cached "
                "decode path is single-mesh; ring attention shards "
                "the sequence dim the cache keeps local)")
        if not causal:
            raise ValueError("cached attention is causal-only")
        att = multi_head_attention_cached(ln1, cache, d_model, num_heads,
                                          key_length=key_length, **kwargs)
    else:
        att = multi_head_attention(ln1, ln1, ln1, d_model, num_heads,
                                   causal=causal, key_length=key_length,
                                   ring_axis=ring_axis, **kwargs)
    if dropout_prob:
        att = _nn.dropout(att, dropout_prob, is_test=is_test, **kwargs)
    x = _nn.elementwise_add(x, att, **kwargs)
    ln2 = _nn.layer_norm(x, begin_norm_axis=2, **kwargs)
    from ..core import unique_name
    prefix = name or unique_name.generate("enc")
    ff = _nn.fc(ln2, d_ff, num_flatten_dims=2, act="gelu",
                param_attr="%s.ffn1.w" % prefix,
                bias_attr="%s.ffn1.b" % prefix, **kwargs)
    ff = _nn.fc(ff, d_model, num_flatten_dims=2,
                param_attr="%s.ffn2.w" % prefix,
                bias_attr="%s.ffn2.b" % prefix, **kwargs)
    if dropout_prob:
        ff = _nn.dropout(ff, dropout_prob, is_test=is_test, **kwargs)
    return _nn.elementwise_add(x, ff, **kwargs)


def positional_encoding(x, max_len=None, name=None, **kwargs):
    """Learned positional embedding added to [B, T, D] input."""
    helper = LayerHelper("pos_encoding", name=name, **kwargs)
    t, d = x.shape[1], x.shape[2]
    pos = helper.create_parameter(
        None, shape=[t, d], dtype=x.dtype,
        default_initializer=NormalInitializer(0.0, 0.02))
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="elementwise_add",
                     inputs={"X": [x.name], "Y": [pos.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": 1})
    return out


def positional_encoding_window(x, max_len, pos=None, window_rows=False,
                               name=None, **kwargs):
    """A window of the SAME learned position table as
    :func:`positional_encoding` (identical parameter name when built
    under the same ``unique_name`` sequence, so a full-sequence train
    program and the cached-decode programs share it):

    * ``pos=None`` (prefill): rows [0, x.shape[1]) of the [max_len, D]
      table are added to x [1, P, D].
    * ``pos`` given (decode): row ``pos[s]`` is gathered per slot and
      added to x [S, 1, D] — one position embedding per in-flight
      sequence, each at its own depth.
    * ``pos`` given with ``window_rows=True`` (paged suffix prefill):
      ``pos`` is one index PER WINDOW ROW ([P], typically
      hist + arange(P)) and the gathered rows are added along x's
      time axis [1, P, D] — a prompt window starting at an arbitrary
      cached depth."""
    helper = LayerHelper("pos_encoding", name=name, **kwargs)
    d = x.shape[2]
    table = helper.create_parameter(
        None, shape=[max_len, d], dtype=x.dtype,
        default_initializer=NormalInitializer(0.0, 0.02))
    out = helper.create_tmp_variable(x.dtype)
    if pos is None:
        t = x.shape[1]
        if t > max_len:
            raise ValueError("prefill window %d exceeds the position "
                             "table length %d" % (t, max_len))
        win = helper.create_tmp_variable(x.dtype)
        helper.append_op(type="slice", inputs={"Input": [table.name]},
                         outputs={"Out": [win.name]},
                         attrs={"axes": [0], "starts": [0],
                                "ends": [t]})
        helper.append_op(type="elementwise_add",
                         inputs={"X": [x.name], "Y": [win.name]},
                         outputs={"Out": [out.name]}, attrs={"axis": 1})
    else:
        rows = helper.create_tmp_variable(x.dtype)
        helper.append_op(type="gather",
                         inputs={"X": [table.name], "Index": [pos.name]},
                         outputs={"Out": [rows.name]})
        rows3 = helper.create_tmp_variable(x.dtype)
        # window mode: rows line up with x's TIME axis [1, P, D];
        # decode mode: one row per slot along the batch axis [S, 1, D]
        shape3 = [1, -1, d] if window_rows else [-1, 1, d]
        helper.append_op(type="reshape", inputs={"X": [rows.name]},
                         outputs={"Out": [rows3.name]},
                         attrs={"shape": shape3})
        helper.append_op(type="elementwise_add",
                         inputs={"X": [x.name], "Y": [rows3.name]},
                         outputs={"Out": [out.name]}, attrs={})
    return out
