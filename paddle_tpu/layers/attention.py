"""Transformer layers: multi-head attention, encoder layer, positional
embedding. (Capability upgrade over the reference's additive-attention NMT
demo; ring_axis enables sequence parallelism over the mesh.)"""

import numpy as np

from ..layer_helper import LayerHelper
from ..initializer import NormalInitializer
from . import nn as _nn
from . import ops as _ops

__all__ = ["multi_head_attention", "transformer_encoder_layer",
           "positional_encoding"]


def multi_head_attention(queries, keys, values, d_model, num_heads,
                         causal=False, key_length=None, ring_axis=None,
                         param_attr=None, name=None, **kwargs):
    """Full MHA with input/output projections. queries/keys/values:
    [B, T, D]. ``ring_axis``: mesh axis name for ring (sequence-parallel)
    attention."""
    helper = LayerHelper("multi_head_attention", name=name, **kwargs)
    # default param names carry tp-able suffixes: .qkv.* weights are
    # column-parallel ([D, D] sharded on dim 1), .o.* row-parallel —
    # see models.transformer.transformer_tp_rules
    from ..core import unique_name
    prefix = name or unique_name.generate("mha")

    def attr(suffix):
        return param_attr if param_attr is not None else \
            "%s.%s.w" % (prefix, suffix)
    q = _nn.fc(queries, d_model, num_flatten_dims=2, bias_attr=False,
               param_attr=attr("qkv_q"), **kwargs)
    k = _nn.fc(keys, d_model, num_flatten_dims=2, bias_attr=False,
               param_attr=attr("qkv_k"), **kwargs)
    v = _nn.fc(values, d_model, num_flatten_dims=2, bias_attr=False,
               param_attr=attr("qkv_v"), **kwargs)
    inputs = {"Q": [q.name], "K": [k.name], "V": [v.name]}
    if key_length is not None:
        inputs["KeyLength"] = [key_length.name]
    ctx_out = helper.create_tmp_variable(queries.dtype)
    helper.append_op(type="multihead_attention", inputs=inputs,
                     outputs={"Out": [ctx_out.name]},
                     attrs={"num_heads": num_heads, "causal": causal,
                            "ring_axis": ring_axis})
    return _nn.fc(ctx_out, d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=attr("o"), **kwargs)


def transformer_encoder_layer(x, d_model, num_heads, d_ff, causal=False,
                              key_length=None, ring_axis=None,
                              dropout_prob=0.0, is_test=False, name=None,
                              **kwargs):
    """Pre-norm transformer block: x + MHA(LN(x)); x + FFN(LN(x))."""
    ln1 = _nn.layer_norm(x, begin_norm_axis=2, **kwargs)
    att = multi_head_attention(ln1, ln1, ln1, d_model, num_heads,
                               causal=causal, key_length=key_length,
                               ring_axis=ring_axis, **kwargs)
    if dropout_prob:
        att = _nn.dropout(att, dropout_prob, is_test=is_test, **kwargs)
    x = _nn.elementwise_add(x, att, **kwargs)
    ln2 = _nn.layer_norm(x, begin_norm_axis=2, **kwargs)
    from ..core import unique_name
    prefix = name or unique_name.generate("enc")
    ff = _nn.fc(ln2, d_ff, num_flatten_dims=2, act="gelu",
                param_attr="%s.ffn1.w" % prefix,
                bias_attr="%s.ffn1.b" % prefix, **kwargs)
    ff = _nn.fc(ff, d_model, num_flatten_dims=2,
                param_attr="%s.ffn2.w" % prefix,
                bias_attr="%s.ffn2.b" % prefix, **kwargs)
    if dropout_prob:
        ff = _nn.dropout(ff, dropout_prob, is_test=is_test, **kwargs)
    return _nn.elementwise_add(x, ff, **kwargs)


def positional_encoding(x, max_len=None, name=None, **kwargs):
    """Learned positional embedding added to [B, T, D] input."""
    helper = LayerHelper("pos_encoding", name=name, **kwargs)
    t, d = x.shape[1], x.shape[2]
    pos = helper.create_parameter(
        None, shape=[t, d], dtype=x.dtype,
        default_initializer=NormalInitializer(0.0, 0.02))
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="elementwise_add",
                     inputs={"X": [x.name], "Y": [pos.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": 1})
    return out
