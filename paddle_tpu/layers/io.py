"""Input layers (reference ``fluid/layers/io.py``)."""

from ..core.framework import default_main_program, convert_dtype

__all__ = ["data"]


def data(name, shape, dtype="float32", append_batch_size=True,
         stop_gradient=True, main_program=None, wire_dtype=None,
         scale=None, mean=None, std=None):
    """Declare a feed variable. ``append_batch_size`` prepends -1 like the
    reference (``fluid/layers/io.py data``).

    wire_dtype: the narrow dtype this feed crosses the host->device wire
    in (e.g. ``"uint8"`` images, ``"int32"`` ids). A feed arriving in
    wire form is kept narrow end-to-end — DataFeeder allocates batch
    buffers in it, reader/staging transfers it — and the executor
    compiles a cast-to-``dtype`` prologue into the step, so the model
    program sees the same widened tensors as the legacy path.
    scale/mean/std: optional per-feed normalize attrs applied on device
    right after the widening cast, as ``(x * scale - mean) / std``;
    scalars or per-channel (axis 1) vectors. They fire only for feeds
    arriving in wire form — an already-widened (host-normalized) feed
    passes through untouched, keeping the f32 path byte-identical.
    """
    program = main_program or default_main_program()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = program.global_block()
    if block.has_var(name):
        var = block.var(name)
        var.shape = tuple(shape)
        var.dtype = convert_dtype(dtype)
    else:
        var = block.create_var(name=name, shape=shape, dtype=dtype,
                               stop_gradient=stop_gradient, is_data=True)
    var.wire_dtype = convert_dtype(wire_dtype) if wire_dtype is not None \
        else None
    var.ingest = {"scale": scale, "mean": mean, "std": std} \
        if (scale is not None or mean is not None or std is not None) \
        else None
    return var
