"""Input layers (reference ``fluid/layers/io.py``)."""

from ..core.framework import default_main_program, convert_dtype

__all__ = ["data"]


def data(name, shape, dtype="float32", append_batch_size=True,
         stop_gradient=True, main_program=None):
    """Declare a feed variable. ``append_batch_size`` prepends -1 like the
    reference (``fluid/layers/io.py data``)."""
    program = main_program or default_main_program()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = program.global_block()
    if block.has_var(name):
        var = block.var(name)
        var.shape = tuple(shape)
        var.dtype = convert_dtype(dtype)
        return var
    return block.create_var(name=name, shape=shape, dtype=dtype,
                            stop_gradient=stop_gradient, is_data=True)
