"""Control-flow layers.

The reference implements While/IfElse/StaticRNN as ops executing sub-blocks
through the interpreter (``operators/while_op.cc``,
``fluid/layers/control_flow.py``). TPU-native control flow compiles to
``lax.scan`` / ``lax.cond`` / ``lax.while_loop`` inside the same XLA
computation. Round 1 ships the scan-based RNNs (layers/sequence.py) plus the
building blocks here; While/StaticRNN sub-block tracing lands with the
seq2seq decoder work.
"""

from ..layer_helper import LayerHelper

__all__ = ["less_than", "equal", "greater_than", "Print"]


def _cmp(op_type, x, y, **kwargs):
    helper = LayerHelper(op_type, **kwargs)
    out = helper.create_tmp_variable("bool", stop_gradient=True)
    helper.append_op(type=op_type, inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]})
    return out


def less_than(x, y, **kwargs):
    return _cmp("less_than", x, y, **kwargs)


def equal(x, y, **kwargs):
    return _cmp("equal", x, y, **kwargs)


def greater_than(x, y, **kwargs):
    return _cmp("greater_than", x, y, **kwargs)


def Print(input, message=None, summarize=20, **kwargs):
    """Debug-print a tensor at execution time (reference print_op) via
    jax.debug.print — works inside the jitted computation."""
    helper = LayerHelper("print", **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="print", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"message": message or input.name,
                            "summarize": summarize})
    return out
