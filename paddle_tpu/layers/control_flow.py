"""Control-flow layers: StaticRNN, While, cond, compares, Print.

Parity with reference ``fluid/layers/control_flow.py`` (StaticRNN, While,
IfElse, less_than, Print) and the legacy recurrent_group
(RecurrentGradientMachine, SURVEY B.3). TPU-native lowering lives in
ops/control_flow_ops.py: StaticRNN -> differentiable lax.scan; While ->
differentiable bounded scan with max_iters, else lax.while_loop
(forward-only); cond -> lax.cond (differentiable).
"""

import contextlib

from ..core import unique_name
from ..layer_helper import LayerHelper

__all__ = ["StaticRNN", "While", "cond", "less_than", "equal",
           "greater_than", "Print", "recompute"]


def _block_external_reads(block):
    """Names read by ``block`` before being written inside it."""
    reads, writes = [], set()
    seen = set()
    from ..core.executor import EMPTY_VAR
    for op in block.ops:
        sub_idx = op.attrs.get("sub_block")
        if sub_idx is not None:
            inner = _block_external_reads(block.program.blocks[sub_idx])
            for n in inner:
                if n not in writes and n not in seen:
                    reads.append(n)
                    seen.add(n)
        for n in op.input_names():
            if n != EMPTY_VAR and n not in writes and n not in seen:
                reads.append(n)
                seen.add(n)
        for n in op.output_names():
            if n != EMPTY_VAR:
                writes.add(n)
    return reads


def _block_writes(block):
    from ..core.executor import EMPTY_VAR
    writes = set()
    for op in block.ops:
        for n in op.output_names():
            if n != EMPTY_VAR:
                writes.add(n)
    return writes


class StaticRNN:
    """Unrolled-over-time RNN builder (reference StaticRNN /
    recurrent_group). Usage::

        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)          # x: [N, T, D]
            h_prev = rnn.memory(init=h0)     # h0: [N, H]
            h = layers.fc([x_t, h_prev], H, act="tanh")
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()                          # [N, T, H]

    Lowers to one lax.scan — fully differentiable, so append_backward /
    optimizer.minimize work through it.
    """

    def __init__(self, name=None, main_program=None, is_reverse=False):
        self.helper = LayerHelper("static_rnn", name=name,
                                  main_program=main_program)
        self.program = self.helper.main_program
        self._step_inputs = []   # (sub var, outer var)
        self._memories = []      # [prev sub var, init outer var, updated]
        self._outputs = []       # sub vars
        self._out_vars = None
        self._final_vars = None
        self.is_reverse = is_reverse

    @contextlib.contextmanager
    def step(self):
        self.parent_block = self.program.current_block()
        self.sub_block = self.program.create_block()
        yield
        self.program.rollback()
        self._complete()

    def step_input(self, x):
        if x.shape is None or len(x.shape) < 2:
            raise ValueError("step_input needs [batch, time, ...] input")
        var = self.sub_block.create_var(
            name=unique_name.generate("rnn.step_in"),
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype)
        self._step_inputs.append((var, x))
        return var

    def memory(self, init):
        prev = self.sub_block.create_var(
            name=unique_name.generate("rnn.mem"),
            shape=init.shape, dtype=init.dtype)
        self._memories.append([prev, init, None])
        return prev

    def update_memory(self, mem, new):
        for entry in self._memories:
            if entry[0] is mem:
                entry[2] = new
                return
        raise ValueError("update_memory: %r is not a memory" % mem.name)

    def step_output(self, o):
        self._outputs.append(o)

    output = step_output  # fluid alias

    def _complete(self):
        for prev, init, updated in self._memories:
            if updated is None:
                raise ValueError("memory %r never updated" % prev.name)
        sub_internal = {v.name for v, _ in self._step_inputs}
        sub_internal |= {m[0].name for m in self._memories}
        captured = [n for n in _block_external_reads(self.sub_block)
                    if n not in sub_internal
                    and self.parent_block.has_var(n)]
        helper = self.helper
        out_vars = [self.parent_block.create_var(
            name=unique_name.generate("rnn.out"), dtype=o.dtype)
            for o in self._outputs]
        final_vars = [self.parent_block.create_var(
            name=unique_name.generate("rnn.final"), dtype=m[0].dtype)
            for m in self._memories]
        self.parent_block.append_op(
            type="static_rnn",
            inputs={
                "StepInputs": [x.name for _, x in self._step_inputs],
                "InitStates": [m[1].name for m in self._memories],
                "Captured": captured,
            },
            outputs={"Outputs": [v.name for v in out_vars],
                     "FinalStates": [v.name for v in final_vars]},
            attrs={"sub_block": self.sub_block.idx,
                   "step_input_vars": [v.name for v, _ in
                                       self._step_inputs],
                   "state_vars": [(m[0].name, m[2].name)
                                  for m in self._memories],
                   "output_vars": [o.name for o in self._outputs],
                   "captured_vars": captured,
                   "is_reverse": self.is_reverse})
        self._out_vars = out_vars
        self._final_vars = final_vars

    def __call__(self):
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return self._out_vars

    def final_states(self):
        return self._final_vars


class While:
    """Run a block until ``cond`` becomes False (reference While /
    while_op). The sub-block must update ``cond`` and may only write vars
    that already exist in the parent (the loop carry). With
    ``max_iters`` the loop is fully differentiable (bounded scan, the
    analog of reference MakeBlockBackward ``framework/backward.cc:353``);
    without it, forward-only (lax.while_loop has no vjp).

    Usage::

        i = layers.fill_constant([1], "int32", 0)
        out = layers.fill_constant([4], "float32", 0.0)
        cond = layers.less_than(i, n)
        w = While(cond)
        with w.block():
            ... compute, assign into out/i ...
            layers.assign(layers.less_than(i, n), cond)
    """

    def __init__(self, cond, name=None, main_program=None,
                 max_iters=None):
        """``max_iters``: static iteration bound. When given, the loop
        lowers to a bounded differentiable scan (finished iterations pass
        state through), so a While-built RNN trains; when None it lowers
        to lax.while_loop (data-dependent trip count, forward-only)."""
        self.helper = LayerHelper("while", name=name,
                                  main_program=main_program)
        self.cond = cond
        self.max_iters = max_iters
        self.program = self.helper.main_program

    @contextlib.contextmanager
    def block(self):
        self.parent_block = self.program.current_block()
        self.sub_block = self.program.create_block()
        yield
        self.program.rollback()
        self._complete()

    def _complete(self):
        writes = _block_writes(self.sub_block)
        # loop state = written vars that exist in the parent (write-back
        # semantics); sub-block-local temporaries die each iteration
        carried = sorted({n for n in writes
                          if self.parent_block.has_var(n)
                          and not self.sub_block.vars.get(n)}
                         | {self.cond.name})
        captured = [n for n in _block_external_reads(self.sub_block)
                    if n not in set(carried)
                    and self.parent_block.has_var(n)]
        if self.max_iters is not None:
            # The bounded loop lowers to a differentiable scan, so float
            # carries are live gradient paths even when their defining op
            # was a constant fill (fill_constant marks its output
            # stop_gradient=True; as a loop carry it is loop *state*, and
            # append_backward must route cotangents into the while op).
            # Only constant-fill outputs are flipped — a user explicitly
            # freezing a non-constant carry keeps stop_gradient.
            from ..core.backward import _float_like
            const_fills = {"fill_constant", "fill_constant_batch_size_like",
                           "fill_like", "assign_value"}
            const_outs = set()
            blk = self.parent_block  # walk the same ancestor chain var()
            while blk is not None:   # resolves through (nested loops)
                const_outs.update(n for op in blk.ops
                                  if op.type in const_fills
                                  for n in op.output_names())
                blk = blk.parent
            for n in carried:
                v = self.parent_block.var(n)
                if n in const_outs and _float_like(self.parent_block, n):
                    v.stop_gradient = False
        self.parent_block.append_op(
            type="while",
            inputs={"Carried": carried, "Captured": captured},
            outputs={"CarriedOut": carried},
            attrs={"sub_block": self.sub_block.idx,
                   "carried_vars": carried,
                   "captured_vars": captured,
                   "cond_var": self.cond.name,
                   "max_iters": self.max_iters},
            infer_shape=False)


def cond(pred, true_fn, false_fn, name=None, main_program=None):
    """Functional conditional (lax.cond; reference IfElse capability).
    ``true_fn``/``false_fn`` build ops and return a Variable or list of
    Variables (same count/shape/dtype both sides)."""
    helper = LayerHelper("cond", name=name, main_program=main_program)
    program = helper.main_program
    parent = program.current_block()

    true_block = program.create_block()
    t_out = true_fn()
    program.rollback()
    false_block = program.create_block()
    f_out = false_fn()
    program.rollback()

    t_out = t_out if isinstance(t_out, (list, tuple)) else [t_out]
    f_out = f_out if isinstance(f_out, (list, tuple)) else [f_out]
    if len(t_out) != len(f_out):
        raise ValueError("cond branches return different arity")

    captured = []
    for blk in (true_block, false_block):
        for n in _block_external_reads(blk):
            if parent.has_var(n) and n not in captured:
                captured.append(n)
    outs = [parent.create_var(name=unique_name.generate("cond.out"),
                              shape=t.shape, dtype=t.dtype)
            for t in t_out]
    parent.append_op(
        type="cond",
        inputs={"Cond": [pred.name], "Captured": captured},
        outputs={"Out": [o.name for o in outs]},
        attrs={"true_block": true_block.idx,
               "false_block": false_block.idx,
               "true_outputs": [v.name for v in t_out],
               "false_outputs": [v.name for v in f_out],
               "captured_vars": captured},
        infer_shape=False)
    return outs[0] if len(outs) == 1 else outs


def _cmp(op_type, x, y, **kwargs):
    helper = LayerHelper(op_type, **kwargs)
    out = helper.create_tmp_variable("bool", stop_gradient=True)
    helper.append_op(type=op_type, inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]})
    return out


def less_than(x, y, **kwargs):
    return _cmp("less_than", x, y, **kwargs)


def equal(x, y, **kwargs):
    return _cmp("equal", x, y, **kwargs)


def greater_than(x, y, **kwargs):
    return _cmp("greater_than", x, y, **kwargs)


def Print(input, message=None, summarize=20, **kwargs):
    """Debug-print a tensor at execution time (reference print_op) via
    jax.debug.print — works inside the jitted computation."""
    helper = LayerHelper("print", **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="print", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"message": message or input.name,
                            "summarize": summarize})
    return out


def recompute(fn, name=None, main_program=None):
    """Gradient checkpointing (rematerialization): run ``fn``'s layers
    with only their INPUTS saved for backward; the vjp recomputes the
    internals (ops/control_flow_ops.py recompute_block ->
    jax.checkpoint). Use around memory-heavy groups (e.g. each ResNet
    residual block) to trade recompute flops for HBM traffic on a
    bandwidth-bound step. ``fn`` must be rng-free.

    Returns fn's Variable (or list/tuple of Variables)."""
    helper = LayerHelper("recompute", name=name,
                         main_program=main_program)
    program = helper.main_program
    parent = program.current_block()
    sub = program.create_block()
    out = fn()
    program.rollback()
    outs = list(out) if isinstance(out, (list, tuple)) else [out]

    captured = [n for n in _block_external_reads(sub)
                if parent.has_var(n)]
    # persistable outer vars the sub-block writes (batch_norm running
    # stats, metric states): surfaced as StateOut so the updates
    # escape the checkpointed scope and the executor persists them
    state_writes = []
    for n in _block_writes(sub):
        v = parent.var(n) if parent.has_var(n) else None
        if v is not None and v.persistable:
            state_writes.append(n)
    state_writes = sorted(state_writes)
    new_outs = []
    for v in outs:
        nv = parent.create_var(
            name=unique_name.generate("recompute.out"),
            shape=v.shape, dtype=v.dtype)
        new_outs.append(nv)
    parent.append_op(
        type="recompute_block",
        inputs={"Captured": captured},
        outputs={"Out": [v.name for v in new_outs],
                 "StateOut": state_writes},
        attrs={"sub_block": sub.idx,
               "captured_vars": captured,
               "output_vars": [v.name for v in outs],
               "state_vars": state_writes},
        infer_shape=False)
    return new_outs[0] if len(new_outs) == 1 else new_outs
