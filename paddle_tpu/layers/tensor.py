"""Tensor-creation / plumbing layers (reference ``fluid/layers/tensor.py``)."""

from ..layer_helper import LayerHelper
from ..core.framework import convert_dtype

__all__ = ["create_tensor", "cast", "concat", "sums", "assign",
           "fill_constant", "fill_constant_batch_size_like", "ones", "zeros",
           "reshape", "transpose", "flip", "split", "expand", "gather", "scatter",
           "pad", "crop", "sequence_reshape_noop", "argmax", "argmin",
           "decode_sample", "decode_verify",
           "stack", "slice", "shape", "increment", "multiplex",
           "array_write", "array_read", "create_array"]


def create_tensor(dtype, name=None, persistable=False, **kwargs):
    helper = LayerHelper("create_tensor", name=name, **kwargs)
    return helper.block.create_var(
        name=name or helper.name, dtype=convert_dtype(dtype),
        persistable=persistable)


def _unary(helper, op_type, x, attrs, dtype=None, slot_in="X"):
    out = helper.create_tmp_variable(dtype or x.dtype)
    helper.append_op(type=op_type, inputs={slot_in: [x.name]},
                     outputs={"Out": [out.name]}, attrs=attrs)
    return out


def cast(x, dtype, **kwargs):
    helper = LayerHelper("cast", **kwargs)
    return _unary(helper, "cast", x, {"out_dtype": dtype},
                  dtype=convert_dtype(dtype))


def concat(input, axis=0, **kwargs):
    helper = LayerHelper("concat", **kwargs)
    out = helper.create_tmp_variable(input[0].dtype)
    helper.append_op(type="concat",
                     inputs={"X": [v.name for v in input]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def sums(input, **kwargs):
    helper = LayerHelper("sum", **kwargs)
    out = helper.create_tmp_variable(input[0].dtype)
    helper.append_op(type="sum", inputs={"X": [v.name for v in input]},
                     outputs={"Out": [out.name]})
    return out


def assign(input, output=None, **kwargs):
    helper = LayerHelper("assign", **kwargs)
    if output is None:
        output = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="assign", inputs={"X": [input.name]},
                     outputs={"Out": [output.name]})
    return output


def fill_constant(shape, dtype, value, out=None, **kwargs):
    helper = LayerHelper("fill_constant", **kwargs)
    if out is None:
        out = helper.create_tmp_variable(dtype, stop_gradient=True)
    helper.append_op(type="fill_constant", outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value)}, infer_shape=False)
    out.shape = tuple(shape)
    out.dtype = convert_dtype(dtype)
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  **kwargs):
    helper = LayerHelper("fill_constant_batch_size_like", **kwargs)
    out = helper.create_tmp_variable(dtype, stop_gradient=True)
    helper.append_op(type="fill_constant_batch_size_like",
                     inputs={"Input": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def ones(shape, dtype="float32", **kwargs):
    return fill_constant(shape, dtype, 1.0, **kwargs)


def zeros(shape, dtype="float32", **kwargs):
    return fill_constant(shape, dtype, 0.0, **kwargs)


def reshape(x, shape, **kwargs):
    helper = LayerHelper("reshape", **kwargs)
    return _unary(helper, "reshape", x, {"shape": list(shape)})


def transpose(x, perm, **kwargs):
    helper = LayerHelper("transpose", **kwargs)
    return _unary(helper, "transpose", x, {"axis": list(perm)})


def flip(x, axis, **kwargs):
    helper = LayerHelper("flip", **kwargs)
    return _unary(helper, "flip", x, {"axis": int(axis)})


def split(input, num_or_sections, dim=0, **kwargs):
    helper = LayerHelper("split", **kwargs)
    if isinstance(num_or_sections, int):
        num, sections = num_or_sections, None
        n_out = num
    else:
        num, sections = None, list(num_or_sections)
        n_out = len(sections)
    outs = [helper.create_tmp_variable(input.dtype) for _ in range(n_out)]
    helper.append_op(type="split", inputs={"X": [input.name]},
                     outputs={"Out": [o.name for o in outs]},
                     attrs={"num": num, "sections": sections, "axis": dim})
    return outs


def expand(x, expand_times, **kwargs):
    helper = LayerHelper("expand", **kwargs)
    return _unary(helper, "expand", x, {"expand_times": list(expand_times)})


def gather(input, index, **kwargs):
    helper = LayerHelper("gather", **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="gather",
                     inputs={"X": [input.name], "Index": [index.name]},
                     outputs={"Out": [out.name]})
    return out


def scatter(input, index, updates, **kwargs):
    helper = LayerHelper("scatter", **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="scatter",
                     inputs={"X": [input.name], "Index": [index.name],
                             "Updates": [updates.name]},
                     outputs={"Out": [out.name]})
    return out


def pad(x, paddings, pad_value=0.0, **kwargs):
    helper = LayerHelper("pad", **kwargs)
    return _unary(helper, "pad", x, {"paddings": list(paddings),
                                     "pad_value": pad_value})


def crop(x, offsets, shape, **kwargs):
    helper = LayerHelper("crop", **kwargs)
    return _unary(helper, "crop", x, {"offsets": list(offsets),
                                      "shape": list(shape)})


def sequence_reshape_noop(x, new_dim, **kwargs):
    """Pure reshape of trailing dim (LoD-free analog of sequence_reshape)."""
    return reshape(x, [-1, new_dim], **kwargs)


def argmax(x, axis=-1, **kwargs):
    helper = LayerHelper("arg_max", **kwargs)
    return _unary(helper, "arg_max", x, {"axis": axis}, dtype="int64")


def argmin(x, axis=-1, **kwargs):
    helper = LayerHelper("arg_min", **kwargs)
    return _unary(helper, "arg_min", x, {"axis": axis}, dtype="int64")


def decode_sample(logits, seed, step, mask=None, temperature=1.0,
                  top_k=0, top_p=1.0, **kwargs):
    """Counter-keyed policy sampling (ops/decoding_ops.py): one token
    per row of ``logits`` [N, V] under ``decoding_key(seed[i],
    step[i])``; optional additive ``mask`` [N, V] for constrained
    decode. Returns [N] int64."""
    helper = LayerHelper("decode_sample", **kwargs)
    inputs = {"Logits": [logits.name], "Seed": [seed.name],
              "Step": [step.name]}
    if mask is not None:
        inputs["Mask"] = [mask.name]
    out = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="decode_sample", inputs=inputs,
                     outputs={"Out": [out.name]},
                     attrs={"temperature": float(temperature),
                            "top_k": int(top_k), "top_p": float(top_p)})
    return out


def decode_verify(logits, window, seed, hist, kind="greedy",
                  temperature=1.0, top_k=0, top_p=1.0, **kwargs):
    """Speculative accept step (ops/decoding_ops.py): target-policy
    tokens at every suffix-window position plus the accepted-draft
    count. Returns (tokens [W] int64, accept [1] int32)."""
    helper = LayerHelper("decode_verify", **kwargs)
    toks = helper.create_tmp_variable("int64", stop_gradient=True)
    accept = helper.create_tmp_variable("int32", stop_gradient=True)
    helper.append_op(
        type="decode_verify",
        inputs={"Logits": [logits.name], "Window": [window.name],
                "Seed": [seed.name], "Hist": [hist.name]},
        outputs={"Tokens": [toks.name], "Accept": [accept.name]},
        attrs={"kind": kind, "temperature": float(temperature),
               "top_k": int(top_k), "top_p": float(top_p)})
    return toks, accept


def stack(x, axis=0, **kwargs):
    helper = LayerHelper("stack", **kwargs)
    out = helper.create_tmp_variable(x[0].dtype)
    helper.append_op(type="stack", inputs={"X": [v.name for v in x]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def slice(input, axes, starts, ends, **kwargs):
    helper = LayerHelper("slice", **kwargs)
    return _unary(helper, "slice", input,
                  {"axes": list(axes), "starts": list(starts),
                   "ends": list(ends)}, slot_in="Input")


def shape(input, **kwargs):
    helper = LayerHelper("shape", **kwargs)
    return _unary(helper, "shape", input, {}, dtype="int64",
                  slot_in="Input")


def increment(x, value=1.0, in_place=True, **kwargs):
    helper = LayerHelper("increment", **kwargs)
    out = x if in_place else helper.create_tmp_variable(x.dtype)
    helper.append_op(type="increment", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"step": value},
                     infer_shape=False)
    return out


def create_array(max_len, elem_shape, dtype="float32", **kwargs):
    """Preallocated [max_len, ...] buffer standing in for the reference's
    LoDTensorArray (static shapes under XLA). Use with array_write /
    array_read inside While loops."""
    return fill_constant([max_len] + list(elem_shape), dtype, 0.0,
                         **kwargs)


def array_write(x, i, array, **kwargs):
    """array[i] = x (runtime index i); returns the updated array
    (reference tensor_array_read_write / fluid layers.array_write)."""
    helper = LayerHelper("array_write", **kwargs)
    out = helper.create_tmp_variable(array.dtype)
    helper.append_op(type="array_write",
                     inputs={"Array": [array.name], "X": [x.name],
                             "I": [i.name]},
                     outputs={"Out": [out.name]})
    return out


def array_read(array, i, **kwargs):
    """Returns array[i] (runtime index; fluid layers.array_read)."""
    helper = LayerHelper("array_read", **kwargs)
    out = helper.create_tmp_variable(array.dtype)
    helper.append_op(type="array_read",
                     inputs={"Array": [array.name], "I": [i.name]},
                     outputs={"Out": [out.name]})
    return out


def multiplex(inputs, index, **kwargs):
    helper = LayerHelper("multiplex", **kwargs)
    out = helper.create_tmp_variable(inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"X": [v.name for v in inputs],
                             "Ids": [index.name]},
                     outputs={"Out": [out.name]})
    return out
