"""Auto-generated unary layer wrappers (reference ``fluid/layers/ops.py``
generates these from OpProtos)."""

from ..layer_helper import LayerHelper

_ACTIVATIONS = [
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink",
    "softshrink", "sqrt", "abs", "ceil", "floor", "round", "reciprocal",
    "log", "square", "softplus", "softsign", "brelu", "leaky_relu",
    "soft_relu", "elu", "relu6", "pow", "stanh", "hard_shrink",
    "thresholded_relu", "hard_sigmoid", "swish", "gelu", "silu", "softmax",
    "sign",
]

__all__ = list(_ACTIVATIONS) + ["scale"]


def _make_unary(op_type):
    def layer(x, name=None, **kwargs):
        attrs = {k: v for k, v in kwargs.items()
                 if k not in ("main_program", "startup_program")}
        helper = LayerHelper(op_type, name=name, **kwargs)
        out = helper.create_tmp_variable(x.dtype)
        helper.append_op(type=op_type, inputs={"X": [x.name]},
                         outputs={"Out": [out.name]}, attrs=attrs)
        return out
    layer.__name__ = op_type
    return layer


for _name in _ACTIVATIONS:
    globals()[_name] = _make_unary(_name)


def scale(x, scale=1.0, bias=0.0, name=None, **kwargs):
    helper = LayerHelper("scale", name=name, **kwargs)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="scale", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"scale": scale, "bias": bias})
    return out
