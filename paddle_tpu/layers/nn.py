"""Core NN layers (reference ``python/paddle/v2/fluid/layers/nn.py``):
fc, embedding, conv2d, conv2d_transpose, pool2d, batch_norm, layer_norm,
dropout, cross_entropy, softmax_with_cross_entropy, accuracy, topk, matmul,
reduce_*, lrn, maxout, l2_normalize, im2sequence ...

Each layer appends ops to the current program; output shapes/dtypes are
inferred by abstract-evaluating the op's JAX compute (registry.infer_shape).
"""

import numpy as np

from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from ..initializer import ConstantInitializer, NormalInitializer

__all__ = [
    "fc", "embedding", "conv2d", "batch_conv2d", "conv3d",
    "conv2d_transpose",
    "conv3d_transpose", "factorization_machine", "pool2d",
    "switch_order", "scale_shift", "resize", "kmax_seq_score",
    "scale_sub_region",
    "pool3d", "batch_norm", "fused_conv_bn", "layer_norm", "dropout",
    "cross_entropy",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "square_error_cost", "accuracy", "auc", "topk", "matmul", "reduce_sum",
    "reduce_mean", "reduce_max", "reduce_min", "reduce_prod", "lrn",
    "maxout", "l2_normalize", "im2sequence", "one_hot", "clip",
    "clip_by_norm", "mean", "mul", "dot_product_attention", "cos_sim",
    "hsigmoid", "nce", "row_conv", "conv_shift", "prelu", "smooth_l1", "log_loss",
    "huber_loss", "hinge_loss", "rank_loss", "margin_rank_loss",
    "bilinear_tensor_product", "spp", "elementwise_add", "elementwise_sub",
    "elementwise_mul", "elementwise_div", "elementwise_max",
    "elementwise_min", "elementwise_pow", "linear_chain_crf",
    "crf_decoding", "warpctc", "edit_distance", "ctc_greedy_decoder",
]


def _single(helper, op_type, inputs, attrs=None, out_slot="Out", dtype=None,
            act=False):
    out = helper.create_tmp_variable(dtype or
                                     helper.block.var(
                                         next(iter(inputs.values()))[0]
                                     ).dtype)
    helper.append_op(type=op_type, inputs=inputs,
                     outputs={out_slot: [out.name]}, attrs=attrs or {})
    return helper.append_activation(out) if act else out


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None, **kwargs):
    """Fully-connected layer (reference fluid/layers/nn.py fc; legacy
    FullyConnectedLayer). Multiple inputs sum their projections."""
    helper = LayerHelper("fc", act=act, name=name, **kwargs)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = ParamAttr.to_attr(param_attr)
    if not isinstance(param_attrs, list):
        param_attrs = [param_attrs] * len(inputs)
    mul_results = []
    for inp, pattr in zip(inputs, param_attrs):
        in_dim = int(np.prod(inp.shape[num_flatten_dims:]))
        w = helper.create_parameter(pattr, shape=[in_dim, size],
                                    dtype=inp.dtype)
        out = helper.create_tmp_variable(inp.dtype)
        helper.append_op(type="mul",
                         inputs={"X": [inp.name], "Y": [w.name]},
                         outputs={"Out": [out.name]},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1})
        mul_results.append(out)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_tmp_variable(mul_results[0].dtype)
        helper.append_op(type="sum",
                         inputs={"X": [v.name for v in mul_results]},
                         outputs={"Out": [pre_bias.name]})
    pre_act = helper.append_bias_op(pre_bias, bias_attr
                                    if bias_attr is not None else
                                    ParamAttr(), dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None,
              keep_dims=False, is_distributed=False, **kwargs):
    """Embedding lookup (reference lookup_table_op). With
    ``is_sparse=True`` the table's gradient is a SelectedRows-style
    (rows, values) pair — never a dense [V, D] buffer — and
    SGD/Momentum/Adagrad/Adam apply row-wise scatter updates
    (ops/sparse_ops.py; reference selected_rows.h).

    ``is_distributed=True`` creates a DistEmbedding table (the pserver
    seam, embeddings/sharded.py): storage is one [padded_vocab, dim]
    array in mod-interleaved layout that DistStrategy row-shards over
    the mesh (``row_id % num_shards`` ownership, flag
    ``embedding_shard_rows``), lookup/gradient exchange runs as a
    two-hop ICI all_to_all inside the jitted step (flag
    ``embedding_a2a``), and the gradient is ALWAYS the sparse
    (rows, values) form. On a single device (or with the flags off) it
    degrades to a numerically identical dense lookup."""
    helper = LayerHelper("embedding", name=name, **kwargs)
    if is_distributed:
        from ..embeddings import sharded as _sharded
        vocab, dim = int(size[0]), int(size[1])
        vp = _sharded.padded_vocab(vocab)
        w = helper.create_parameter(
            param_attr, shape=[vp, dim], dtype=dtype,
            default_initializer=NormalInitializer(0.0,
                                                  1.0 / np.sqrt(dim)))
        _sharded.register_table(helper.main_program, w.name,
                                vocab=vocab, padded=vp, dim=dim)
        out = helper.create_tmp_variable(dtype)
        helper.append_op(type="lookup_table_dist",
                         inputs={"W": [w.name], "Ids": [input.name]},
                         outputs={"Out": [out.name]},
                         attrs={"padding_idx": padding_idx,
                                "vocab_size": vocab,
                                "padded_vocab": vp,
                                "keep_dims": bool(keep_dims)})
        return out
    w = helper.create_parameter(param_attr, shape=list(size), dtype=dtype,
                                default_initializer=NormalInitializer(
                                    0.0, 1.0 / np.sqrt(size[1])))
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="lookup_table",
                     inputs={"W": [w.name], "Ids": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"padding_idx": padding_idx,
                            "is_sparse": bool(is_sparse),
                            "keep_dims": bool(keep_dims)})
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           **kwargs):
    helper = LayerHelper("conv2d", act=act, name=name, **kwargs)
    num_channels = input.shape[1]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) \
        else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) \
        else list(dilation)
    fan_in = num_channels * int(np.prod(filter_size)) // (groups or 1)
    w = helper.create_parameter(
        param_attr,
        shape=[num_filters, num_channels // (groups or 1)] +
        list(filter_size),
        dtype=input.dtype,
        default_initializer=NormalInitializer(0.0,
                                              float(np.sqrt(2.0 / fan_in))))
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="conv2d",
                     inputs={"Input": [input.name], "Filter": [w.name]},
                     outputs={"Output": [out.name]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups or 1})
    if bias_attr is not False:
        bias = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                       shape=[num_filters],
                                       dtype=input.dtype, is_bias=True)
        tmp = helper.create_tmp_variable(input.dtype)
        helper.append_op(type="elementwise_add",
                         inputs={"X": [out.name], "Y": [bias.name]},
                         outputs={"Out": [tmp.name]}, attrs={"axis": 1})
        out = tmp
    return helper.append_activation(out)


def batch_conv2d(input, filter, stride=1, padding=0, dilation=1,
                 name=None, **kwargs):
    """Conv with a DATA-DEPENDENT filter: ``filter`` is another
    variable's output, [B, O, C, kh, kw] — each batch row of ``input``
    [B, C, H, W] is convolved with its own filter (reference
    ConvOperator, gserver/layers/ConvOperator.cpp:59)."""
    helper = LayerHelper("batch_conv2d", name=name, **kwargs)
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) \
        else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) \
        else list(dilation)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="batch_conv2d",
                     inputs={"Input": [input.name],
                             "Filter": [filter.name]},
                     outputs={"Output": [out.name]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation})
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           **kwargs):
    helper = LayerHelper("conv3d", act=act, name=name, **kwargs)
    num_channels = input.shape[1]
    fs = [filter_size] * 3 if isinstance(filter_size, int) \
        else list(filter_size)
    stride = [stride] * 3 if isinstance(stride, int) else list(stride)
    padding = [padding] * 3 if isinstance(padding, int) else list(padding)
    w = helper.create_parameter(
        param_attr, shape=[num_filters, num_channels // (groups or 1)] + fs,
        dtype=input.dtype)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="conv3d",
                     inputs={"Input": [input.name], "Filter": [w.name]},
                     outputs={"Output": [out.name]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": [1, 1, 1], "groups": groups or 1})
    if bias_attr is not False:
        bias = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                       shape=[num_filters],
                                       dtype=input.dtype, is_bias=True)
        tmp = helper.create_tmp_variable(input.dtype)
        helper.append_op(type="elementwise_add",
                         inputs={"X": [out.name], "Y": [bias.name]},
                         outputs={"Out": [tmp.name]}, attrs={"axis": 1})
        out = tmp
    return helper.append_activation(out)


def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, param_attr=None, bias_attr=None, act=None,
                     name=None, **kwargs):
    helper = LayerHelper("conv2d_transpose", act=act, name=name, **kwargs)
    num_channels = input.shape[1]
    fs = [filter_size] * 2 if isinstance(filter_size, int) \
        else list(filter_size)
    stride = [stride] * 2 if isinstance(stride, int) else list(stride)
    padding = [padding] * 2 if isinstance(padding, int) else list(padding)
    dilation = [dilation] * 2 if isinstance(dilation, int) \
        else list(dilation)
    w = helper.create_parameter(param_attr,
                                shape=[num_channels, num_filters] + fs,
                                dtype=input.dtype)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="conv2d_transpose",
                     inputs={"Input": [input.name], "Filter": [w.name]},
                     outputs={"Output": [out.name]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation})
    if bias_attr is not False:
        out = helper.append_bias_op(out, ParamAttr.to_attr(bias_attr),
                                    dim_start=1, dim_end=2)
    return helper.append_activation(out)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, name=None, **kwargs):
    helper = LayerHelper("pool2d", name=name, **kwargs)
    ps = [pool_size] * 2 if isinstance(pool_size, int) else list(pool_size)
    st = [pool_stride] * 2 if isinstance(pool_stride, int) \
        else list(pool_stride)
    pd = [pool_padding] * 2 if isinstance(pool_padding, int) \
        else list(pool_padding)
    return _single(helper, "pool2d", {"X": [input.name]},
                   {"ksize": ps, "strides": st, "paddings": pd,
                    "pooling_type": pool_type,
                    "global_pooling": global_pooling,
                    "ceil_mode": ceil_mode, "exclusive": exclusive})


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, name=None, **kwargs):
    helper = LayerHelper("pool3d", name=name, **kwargs)
    ps = [pool_size] * 3 if isinstance(pool_size, int) else list(pool_size)
    st = [pool_stride] * 3 if isinstance(pool_stride, int) \
        else list(pool_stride)
    pd = [pool_padding] * 3 if isinstance(pool_padding, int) \
        else list(pool_padding)
    return _single(helper, "pool3d", {"X": [input.name]},
                   {"ksize": ps, "strides": st, "paddings": pd,
                    "pooling_type": pool_type,
                    "global_pooling": global_pooling})


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, **kwargs):
    """BatchNorm with persistable running stats updated in-graph (reference
    batch_norm_op.cc; cross-replica sync handled by the data-parallel
    executor via mean-gradient + local stats, see parallel/)."""
    helper = LayerHelper("batch_norm", act=act, name=name, **kwargs)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(
        param_attr, shape=[c], dtype=input.dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, shape=[c], dtype=input.dtype,
                                   is_bias=True)
    mean = helper.create_global_variable(
        shape=[c], dtype=input.dtype, persistable=True,
        name=helper.name + ".mean" if name else None,
        initializer=ConstantInitializer(0.0))
    variance = helper.create_global_variable(
        shape=[c], dtype=input.dtype, persistable=True,
        name=helper.name + ".variance" if name else None,
        initializer=ConstantInitializer(1.0))
    out = helper.create_tmp_variable(input.dtype)
    saved_mean = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    saved_var = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    helper.append_op(type="batch_norm",
                     inputs={"X": [input.name], "Scale": [scale.name],
                             "Bias": [bias.name], "Mean": [mean.name],
                             "Variance": [variance.name]},
                     outputs={"Y": [out.name], "MeanOut": [mean.name],
                              "VarianceOut": [variance.name],
                              "SavedMean": [saved_mean.name],
                              "SavedVariance": [saved_var.name]},
                     attrs={"momentum": momentum, "epsilon": epsilon,
                            "is_test": is_test,
                            "data_layout": data_layout})
    return helper.append_activation(out)


def fused_conv_bn(input, num_filters, filter_size, stride=1, padding=0,
                  dilation=1, groups=1, act=None, is_test=False,
                  momentum=0.9, epsilon=1e-5, param_attr=None,
                  bn_param_attr=None, bn_bias_attr=None, name=None,
                  **kwargs):
    """conv2d (bias-free) + batch_norm as ONE ``conv2d_bn`` op
    (ops/pallas_conv_bn.py): the conv output is written once with its
    batch moments accumulated in the same pass instead of re-read by a
    separate batch_norm. Parameter/initializer layout matches the
    unfused pair (conv filter with He init, BN scale/bias, persistable
    running mean/variance), so checkpoints interchange."""
    helper = LayerHelper("conv2d_bn", act=act, name=name, **kwargs)
    num_channels = input.shape[1]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) \
        else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) \
        else list(dilation)
    fan_in = num_channels * int(np.prod(filter_size)) // (groups or 1)
    w = helper.create_parameter(
        param_attr,
        shape=[num_filters, num_channels // (groups or 1)] +
        list(filter_size),
        dtype=input.dtype,
        default_initializer=NormalInitializer(0.0,
                                              float(np.sqrt(2.0 / fan_in))))
    scale = helper.create_parameter(
        bn_param_attr, shape=[num_filters], dtype=input.dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bn_bias_attr, shape=[num_filters],
                                   dtype=input.dtype, is_bias=True)
    mean = helper.create_global_variable(
        shape=[num_filters], dtype=input.dtype, persistable=True,
        name=helper.name + ".mean" if name else None,
        initializer=ConstantInitializer(0.0))
    variance = helper.create_global_variable(
        shape=[num_filters], dtype=input.dtype, persistable=True,
        name=helper.name + ".variance" if name else None,
        initializer=ConstantInitializer(1.0))
    out = helper.create_tmp_variable(input.dtype)
    saved_mean = helper.create_tmp_variable(input.dtype,
                                            stop_gradient=True)
    saved_var = helper.create_tmp_variable(input.dtype,
                                           stop_gradient=True)
    helper.append_op(type="conv2d_bn",
                     inputs={"Input": [input.name], "Filter": [w.name],
                             "Scale": [scale.name], "Bias": [bias.name],
                             "Mean": [mean.name],
                             "Variance": [variance.name]},
                     outputs={"Y": [out.name], "MeanOut": [mean.name],
                              "VarianceOut": [variance.name],
                              "SavedMean": [saved_mean.name],
                              "SavedVariance": [saved_var.name]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation, "groups": groups or 1,
                            "momentum": momentum, "epsilon": epsilon,
                            "is_test": is_test})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None, **kwargs):
    helper = LayerHelper("layer_norm", act=act, name=name, **kwargs)
    norm_shape = list(input.shape[begin_norm_axis:])
    inputs = {"X": [input.name]}
    if scale:
        s = helper.create_parameter(
            param_attr, shape=norm_shape, dtype=input.dtype,
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s.name]
    if shift:
        b = helper.create_parameter(bias_attr, shape=norm_shape,
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    out = helper.create_tmp_variable(input.dtype)
    mean = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    var = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    helper.append_op(type="layer_norm", inputs=inputs,
                     outputs={"Y": [out.name], "Mean": [mean.name],
                              "Variance": [var.name]},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def dropout(x, dropout_prob=0.5, is_test=False, name=None, **kwargs):
    helper = LayerHelper("dropout", name=name, **kwargs)
    out = helper.create_tmp_variable(x.dtype)
    mask = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    helper.append_op(type="dropout", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Mask": [mask.name]},
                     attrs={"dropout_prob": dropout_prob,
                            "is_test": is_test})
    return out


def cross_entropy(input, label, soft_label=False, name=None, **kwargs):
    helper = LayerHelper("cross_entropy", name=name, **kwargs)
    return _single(helper, "cross_entropy",
                   {"X": [input.name], "Label": [label.name]},
                   {"soft_label": soft_label}, out_slot="Y",
                   dtype=input.dtype)


def softmax_with_cross_entropy(logits, label, soft_label=False, name=None,
                               **kwargs):
    helper = LayerHelper("softmax_with_cross_entropy", name=name, **kwargs)
    softmax = helper.create_tmp_variable(logits.dtype)
    loss = helper.create_tmp_variable(logits.dtype)
    helper.append_op(type="softmax_with_cross_entropy",
                     inputs={"Logits": [logits.name],
                             "Label": [label.name]},
                     outputs={"Softmax": [softmax.name],
                              "Loss": [loss.name]},
                     attrs={"soft_label": soft_label})
    return loss


def sigmoid_cross_entropy_with_logits(x, label, name=None, **kwargs):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name,
                         **kwargs)
    return _single(helper, "sigmoid_cross_entropy_with_logits",
                   {"X": [x.name], "Label": [label.name]})


def square_error_cost(input, label, name=None, **kwargs):
    helper = LayerHelper("square_error_cost", name=name, **kwargs)
    return _single(helper, "square_error_cost",
                   {"X": [input.name], "Y": [label.name]})


def accuracy(input, label, k=1, name=None, **kwargs):
    """Batch accuracy from predictions (reference accuracy_op +
    fluid/layers accuracy): runs top_k then compares."""
    helper = LayerHelper("accuracy", name=name, **kwargs)
    topk_out = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    topk_idx = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="top_k", inputs={"X": [input.name]},
                     outputs={"Out": [topk_out.name],
                              "Indices": [topk_idx.name]},
                     attrs={"k": k})
    acc = helper.create_tmp_variable("float32", stop_gradient=True)
    correct = helper.create_tmp_variable("int64", stop_gradient=True)
    total = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="accuracy",
                     inputs={"Indices": [topk_idx.name],
                             "Label": [label.name]},
                     outputs={"Accuracy": [acc.name],
                              "Correct": [correct.name],
                              "Total": [total.name]})
    return acc


def auc(input, label, num_thresholds=200, name=None, **kwargs):
    helper = LayerHelper("auc", name=name, **kwargs)
    out = helper.create_tmp_variable("float32", stop_gradient=True)
    helper.append_op(type="auc",
                     inputs={"Out": [input.name], "Label": [label.name]},
                     outputs={"AUC": [out.name]},
                     attrs={"num_thresholds": num_thresholds})
    return out


def topk(input, k=1, name=None, **kwargs):
    helper = LayerHelper("top_k", name=name, **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    idx = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="top_k", inputs={"X": [input.name]},
                     outputs={"Out": [out.name], "Indices": [idx.name]},
                     attrs={"k": k})
    return out, idx


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None,
           **kwargs):
    helper = LayerHelper("matmul", name=name, **kwargs)
    return _single(helper, "matmul", {"X": [x.name], "Y": [y.name]},
                   {"transpose_X": transpose_x, "transpose_Y": transpose_y,
                    "alpha": alpha})


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None, **kwargs):
    helper = LayerHelper("mul", name=name, **kwargs)
    return _single(helper, "mul", {"X": [x.name], "Y": [y.name]},
                   {"x_num_col_dims": x_num_col_dims,
                    "y_num_col_dims": y_num_col_dims})


def _make_reduce(op_name):
    def layer(input, dim=None, keep_dim=False, name=None, **kwargs):
        helper = LayerHelper(op_name, name=name, **kwargs)
        return _single(helper, op_name, {"X": [input.name]},
                       {"dim": dim, "keep_dim": keep_dim,
                        "reduce_all": dim is None})
    layer.__name__ = op_name
    return layer


reduce_sum = _make_reduce("reduce_sum")
reduce_mean = _make_reduce("reduce_mean")
reduce_max = _make_reduce("reduce_max")
reduce_min = _make_reduce("reduce_min")
reduce_prod = _make_reduce("reduce_prod")


def mean(x, name=None, **kwargs):
    helper = LayerHelper("mean", name=name, **kwargs)
    return _single(helper, "mean", {"X": [x.name]})


def lrn(input, n=5, k=2.0, alpha=1e-4, beta=0.75, name=None, **kwargs):
    helper = LayerHelper("lrn", name=name, **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    mid = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    helper.append_op(type="lrn", inputs={"X": [input.name]},
                     outputs={"Out": [out.name], "MidOut": [mid.name]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def maxout(x, groups, name=None, **kwargs):
    helper = LayerHelper("maxout", name=name, **kwargs)
    return _single(helper, "maxout", {"X": [x.name]}, {"groups": groups})


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None, **kwargs):
    helper = LayerHelper("l2_normalize", name=name, **kwargs)
    out = helper.create_tmp_variable(x.dtype)
    norm = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    helper.append_op(type="l2_normalize", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Norm": [norm.name]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def im2sequence(input, filter_size=1, stride=1, name=None, **kwargs):
    helper = LayerHelper("im2sequence", name=name, **kwargs)
    fs = [filter_size] * 2 if isinstance(filter_size, int) \
        else list(filter_size)
    st = [stride] * 2 if isinstance(stride, int) else list(stride)
    return _single(helper, "im2sequence", {"X": [input.name]},
                   {"kernels": fs, "strides": st})


def one_hot(input, depth, name=None, **kwargs):
    helper = LayerHelper("one_hot", name=name, **kwargs)
    return _single(helper, "one_hot", {"X": [input.name]},
                   {"depth": depth}, dtype="float32")


def clip(x, min, max, name=None, **kwargs):
    helper = LayerHelper("clip", name=name, **kwargs)
    return _single(helper, "clip", {"X": [x.name]},
                   {"min": min, "max": max})


def clip_by_norm(x, max_norm, name=None, **kwargs):
    helper = LayerHelper("clip_by_norm", name=name, **kwargs)
    return _single(helper, "clip_by_norm", {"X": [x.name]},
                   {"max_norm": max_norm})


def cos_sim(x, y, name=None, **kwargs):
    helper = LayerHelper("cos_sim", name=name, **kwargs)
    out = helper.create_tmp_variable(x.dtype)
    xn = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    yn = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    helper.append_op(type="cos_sim",
                     inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name], "XNorm": [xn.name],
                              "YNorm": [yn.name]})
    return out


def dot_product_attention(querys, keys, values, name=None, **kwargs):
    """Scaled dot-product attention (reference fluid/nets.py
    scaled_dot_product_attention)."""
    helper = LayerHelper("dot_product_attention", name=name, **kwargs)
    logits = matmul(querys, keys, transpose_y=True,
                    alpha=1.0 / np.sqrt(keys.shape[-1]), **kwargs)
    weights = _single(helper, "softmax", {"X": [logits.name]})
    ctx = matmul(weights, values, **kwargs)
    return ctx, weights


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, **kwargs):
    """Hierarchical sigmoid over a complete binary tree (reference
    hierarchical_sigmoid / MatrixBitCode). Dense-path TPU implementation."""
    helper = LayerHelper("hsigmoid", name=name, **kwargs)
    w = helper.create_parameter(param_attr,
                                shape=[num_classes - 1, input.shape[-1]],
                                dtype=input.dtype)
    inputs = {"X": [input.name], "W": [w.name], "Label": [label.name]}
    if bias_attr is not False:
        bias = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                       shape=[num_classes - 1],
                                       dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [bias.name]
    return _single(helper, "hsigmoid", inputs,
                   {"num_classes": num_classes}, dtype=input.dtype)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        **kwargs):
    helper = LayerHelper("nce", name=name, **kwargs)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype)
    inputs = {"Input": [input.name], "Label": [label.name],
              "Weight": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                    shape=[num_total_classes, 1],
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    cost = helper.create_tmp_variable(input.dtype)
    logits = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    labels = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="nce", inputs=inputs,
                     outputs={"Cost": [cost.name],
                              "SampleLogits": [logits.name],
                              "SampleLabels": [labels.name]},
                     attrs={"num_neg_samples": num_neg_samples,
                            "num_total_classes": num_total_classes})
    return cost


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None, **kwargs):
    helper = LayerHelper("row_conv", act=act, name=name, **kwargs)
    w = helper.create_parameter(param_attr,
                                shape=[future_context_size + 1,
                                       input.shape[-1]],
                                dtype=input.dtype)
    return _single(helper, "row_conv",
                   {"X": [input.name], "Filter": [w.name]}, act=True)


def prelu(x, param_attr=None, mode="all", name=None, **kwargs):
    helper = LayerHelper("prelu", name=name, **kwargs)
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [x.shape[1]] + [1] * (len(x.shape) - 2)
    else:
        shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        param_attr, shape=shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25))
    return _single(helper, "prelu",
                   {"X": [x.name], "Alpha": [alpha.name]})


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0,
              name=None, **kwargs):
    helper = LayerHelper("smooth_l1", name=name, **kwargs)
    inputs = {"X": [x.name], "Y": [y.name]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight.name]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight.name]
    out = helper.create_tmp_variable(x.dtype)
    diff = helper.create_tmp_variable(x.dtype, stop_gradient=True)
    helper.append_op(type="smooth_l1_loss", inputs=inputs,
                     outputs={"Out": [out.name], "Diff": [diff.name]},
                     attrs={"sigma": sigma})
    return out


def log_loss(input, label, epsilon=1e-4, name=None, **kwargs):
    helper = LayerHelper("log_loss", name=name, **kwargs)
    return _single(helper, "log_loss",
                   {"Predicted": [input.name], "Labels": [label.name]},
                   {"epsilon": epsilon}, out_slot="Loss",
                   dtype=input.dtype)


def huber_loss(input, label, delta=1.0, name=None, **kwargs):
    helper = LayerHelper("huber_loss", name=name, **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    resid = helper.create_tmp_variable(input.dtype, stop_gradient=True)
    helper.append_op(type="huber_loss",
                     inputs={"X": [input.name], "Y": [label.name]},
                     outputs={"Out": [out.name], "Residual": [resid.name]},
                     attrs={"delta": delta})
    return out


def hinge_loss(input, label, name=None, **kwargs):
    helper = LayerHelper("hinge_loss", name=name, **kwargs)
    return _single(helper, "hinge_loss",
                   {"Logits": [input.name], "Labels": [label.name]},
                   out_slot="Loss", dtype=input.dtype)


def conv_shift(x, y, name=None, **kwargs):
    """Circular 1-D correlation (reference conv_shift_op / v2
    conv_shift_layer): out[b, i] = sum_j x[b, (i+j-M/2) mod N] * y[b, j]."""
    helper = LayerHelper("conv_shift", name=name, **kwargs)
    return _single(helper, "conv_shift",
                   {"X": [x.name], "Y": [y.name]}, {})


def rank_loss(left, right, label, name=None, **kwargs):
    helper = LayerHelper("rank_loss", name=name, **kwargs)
    return _single(helper, "rank_loss",
                   {"Left": [left.name], "Right": [right.name],
                    "Label": [label.name]}, dtype=left.dtype)


def margin_rank_loss(label, left, right, margin=0.1, name=None, **kwargs):
    helper = LayerHelper("margin_rank_loss", name=name, **kwargs)
    out = helper.create_tmp_variable(left.dtype)
    act = helper.create_tmp_variable(left.dtype, stop_gradient=True)
    helper.append_op(type="margin_rank_loss",
                     inputs={"X1": [left.name], "X2": [right.name],
                             "Label": [label.name]},
                     outputs={"Out": [out.name], "Activated": [act.name]},
                     attrs={"margin": margin})
    return out


def bilinear_tensor_product(x, y, size, param_attr=None, bias_attr=None,
                            act=None, name=None, **kwargs):
    helper = LayerHelper("bilinear_tensor_product", act=act, name=name,
                         **kwargs)
    w = helper.create_parameter(param_attr,
                                shape=[size, x.shape[-1], y.shape[-1]],
                                dtype=x.dtype)
    inputs = {"X": [x.name], "Y": [y.name], "Weight": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                    shape=[size], dtype=x.dtype,
                                    is_bias=True)
        inputs["Bias"] = [b.name]
    return _single(helper, "bilinear_tensor_product", inputs, act=True,
                   dtype=x.dtype)


def spp(input, pyramid_height=3, pool_type="max", name=None, **kwargs):
    helper = LayerHelper("spp", name=name, **kwargs)
    return _single(helper, "spp", {"X": [input.name]},
                   {"pyramid_height": pyramid_height,
                    "pooling_type": pool_type})


def _make_elementwise(op_name):
    def layer(x, y, axis=-1, act=None, name=None, **kwargs):
        helper = LayerHelper(op_name, act=act, name=name, **kwargs)
        return _single(helper, op_name,
                       {"X": [x.name], "Y": [y.name]}, {"axis": axis},
                       act=True)
    layer.__name__ = op_name
    return layer


elementwise_add = _make_elementwise("elementwise_add")
elementwise_sub = _make_elementwise("elementwise_sub")
elementwise_mul = _make_elementwise("elementwise_mul")
elementwise_div = _make_elementwise("elementwise_div")
elementwise_max = _make_elementwise("elementwise_max")
elementwise_min = _make_elementwise("elementwise_min")
elementwise_pow = _make_elementwise("elementwise_pow")


def linear_chain_crf(input, label, length=None, param_attr=None,
                     name=None, **kwargs):
    """CRF negative log-likelihood cost (reference
    fluid/layers linear_chain_crf). input: [N,T,C] emissions."""
    helper = LayerHelper("linear_chain_crf", name=name, **kwargs)
    num_classes = input.shape[-1]
    transition = helper.create_parameter(
        param_attr, shape=[num_classes + 2, num_classes],
        dtype=input.dtype,
        default_initializer=NormalInitializer(0.0, 0.1))
    inputs = {"Emission": [input.name], "Label": [label.name],
              "Transition": [transition.name]}
    if length is not None:
        inputs["Length"] = [length.name]
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="linear_chain_crf", inputs=inputs,
                     outputs={"LogLikelihood": [out.name]})
    return out


def crf_decoding(input, param_attr, length=None, name=None, **kwargs):
    """Viterbi decode using a trained CRF transition parameter."""
    helper = LayerHelper("crf_decoding", name=name, **kwargs)
    transition = helper.create_parameter(
        ParamAttr.to_attr(param_attr),
        shape=[input.shape[-1] + 2, input.shape[-1]], dtype=input.dtype)
    inputs = {"Emission": [input.name], "Transition": [transition.name]}
    if length is not None:
        inputs["Length"] = [length.name]
    out = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [out.name]})
    return out


def warpctc(input, label, logits_length, label_length, blank=0,
            norm_by_times=False, name=None, **kwargs):
    """CTC loss (reference warpctc layer). input: [N,T,C] logits."""
    helper = LayerHelper("warpctc", name=name, **kwargs)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="warpctc",
                     inputs={"Logits": [input.name],
                             "Label": [label.name],
                             "LogitsLength": [logits_length.name],
                             "LabelLength": [label_length.name]},
                     outputs={"Loss": [out.name]},
                     attrs={"blank": blank,
                            "norm_by_times": norm_by_times})
    return out


def edit_distance(input, label, input_length, label_length,
                  normalized=True, name=None, **kwargs):
    helper = LayerHelper("edit_distance", name=name, **kwargs)
    out = helper.create_tmp_variable("float32", stop_gradient=True)
    seq_num = helper.create_tmp_variable("int32", stop_gradient=True)
    helper.append_op(type="edit_distance",
                     inputs={"Hyps": [input.name], "Refs": [label.name],
                             "HypsLength": [input_length.name],
                             "RefsLength": [label_length.name]},
                     outputs={"Out": [out.name],
                              "SequenceNum": [seq_num.name]},
                     attrs={"normalized": normalized})
    return out, seq_num


def ctc_greedy_decoder(input, blank, length, name=None, **kwargs):
    """argmax over classes then CTC-align (merge repeats, drop blanks)."""
    helper = LayerHelper("ctc_greedy_decoder", name=name, **kwargs)
    ids = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="arg_max", inputs={"X": [input.name]},
                     outputs={"Out": [ids.name]}, attrs={"axis": -1})
    out = helper.create_tmp_variable("int64", stop_gradient=True)
    out_len = helper.create_tmp_variable("int64", stop_gradient=True)
    helper.append_op(type="ctc_align",
                     inputs={"Input": [ids.name],
                             "Length": [length.name]},
                     outputs={"Output": [out.name],
                              "OutputLength": [out_len.name]},
                     attrs={"blank": blank})
    return out, out_len


def conv3d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, param_attr=None, bias_attr=None, act=None,
                     name=None, **kwargs):
    """3-D transposed conv (reference conv3d_transpose /
    conv_transpose_op.cc)."""
    helper = LayerHelper("conv3d_transpose", act=act, name=name, **kwargs)
    num_channels = input.shape[1]
    fs = [filter_size] * 3 if isinstance(filter_size, int) \
        else list(filter_size)
    stride = [stride] * 3 if isinstance(stride, int) else list(stride)
    padding = [padding] * 3 if isinstance(padding, int) else list(padding)
    dilation = [dilation] * 3 if isinstance(dilation, int) \
        else list(dilation)
    w = helper.create_parameter(param_attr,
                                shape=[num_channels, num_filters] + fs,
                                dtype=input.dtype)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="conv3d_transpose",
                     inputs={"Input": [input.name], "Filter": [w.name]},
                     outputs={"Output": [out.name]},
                     attrs={"strides": stride, "paddings": padding,
                            "dilations": dilation})
    if bias_attr is not False:
        out = helper.append_bias_op(out, ParamAttr.to_attr(bias_attr),
                                    dim_start=1, dim_end=2)
    return helper.append_activation(out)


def factorization_machine(input, factor_size, param_attr=None, act=None,
                          name=None, **kwargs):
    """Second-order factorization machine interaction term (reference
    FactorizationMachineLayer.cpp): out[n] = 0.5 * sum_k((x@V)_k^2 -
    (x^2@V^2)_k). Combine with an fc for the linear term."""
    helper = LayerHelper("factorization_machine", act=act, name=name,
                         **kwargs)
    dim = input.shape[-1]
    v = helper.create_parameter(param_attr, shape=[dim, factor_size],
                                dtype=input.dtype)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="factorization_machine",
                     inputs={"X": [input.name], "V": [v.name]},
                     outputs={"Out": [out.name]})
    return helper.append_activation(out)


def switch_order(input, to_nhwc=True, name=None, **kwargs):
    """NCHW <-> NHWC layout switch (reference SwitchOrderLayer)."""
    helper = LayerHelper("switch_order", name=name, **kwargs)
    return _single(helper, "switch_order", {"X": [input.name]},
                   {"to_nhwc": to_nhwc})


def scale_shift(input, param_attr=None, bias_attr=None, name=None,
                **kwargs):
    """y = w*x + b with trainable scalar w (and b unless bias_attr is
    False) — reference ScaleShiftLayer."""
    helper = LayerHelper("scale_shift", name=name, **kwargs)
    w = helper.create_parameter(param_attr, shape=[1], dtype=input.dtype)
    inputs = {"X": [input.name], "Scale": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(ParamAttr.to_attr(bias_attr),
                                    shape=[1], dtype=input.dtype,
                                    is_bias=True)
        inputs["Bias"] = [b.name]
    return _single(helper, "scale_shift", inputs, {})


def resize(input, size, name=None, **kwargs):
    """Reshape rows to trailing ``size`` (reference ResizeLayer)."""
    helper = LayerHelper("resize", name=name, **kwargs)
    return _single(helper, "resize", {"X": [input.name]},
                   {"size": size})


def kmax_seq_score(input, length=None, beam_size=1, name=None,
                   **kwargs):
    """Top-k score indices per padded sequence (reference
    KmaxSeqScoreLayer); -1 marks slots past a sequence's k."""
    helper = LayerHelper("kmax_seq_score", name=name, **kwargs)
    inputs = {"X": [input.name]}
    if length is not None:
        inputs["Length"] = [length.name]
    out = helper.create_tmp_variable("int32", stop_gradient=True)
    helper.append_op(type="kmax_seq_score", inputs=inputs,
                     outputs={"Out": [out.name]},
                     attrs={"beam_size": beam_size})
    return out


def scale_sub_region(input, indices, value=1.0, name=None, **kwargs):
    """Scale a per-sample NCHW sub-region by ``value`` (reference
    ScaleSubRegionLayer; indices [N,6] 1-based inclusive
    (c1,c2,h1,h2,w1,w2))."""
    helper = LayerHelper("scale_sub_region", name=name, **kwargs)
    return _single(helper, "scale_sub_region",
                   {"X": [input.name], "Indices": [indices.name]},
                   {"value": value})
