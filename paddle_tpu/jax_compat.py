"""Shims over jax API drift so one source tree spans jax versions.

``shard_map`` moved from ``jax.experimental.shard_map`` to the ``jax``
top level, and its replication-check kwarg was renamed
``check_rep`` -> ``check_vma`` along the way. Callers here use the new
spelling; the shim translates for older jax.
"""

import inspect

try:  # jax >= 0.6: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # older jax: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

# The kwarg rename and the top-level promotion happened in different
# releases — detect the accepted name from the signature, not the
# import path.
_CHECK_KW = "check_vma" if "check_vma" in inspect.signature(
    _shard_map).parameters else "check_rep"

__all__ = ["shard_map"]


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    kwargs = {} if check_vma is None else {_CHECK_KW: check_vma}
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
