"""Core: IR (framework), registry, executor, backward, scope."""

from . import unique_name  # noqa: F401
from .framework import (  # noqa: F401
    Program, Block, Operator, Variable, Parameter,
    default_main_program, default_startup_program, program_guard,
    switch_main_program, switch_startup_program, convert_dtype)
from .scope import Scope, global_scope, scope_guard  # noqa: F401
from .executor import Executor  # noqa: F401
from .backward import append_backward, grad_var_name  # noqa: F401
