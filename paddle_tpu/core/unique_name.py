"""Unique name generation for variables/ops.

Capability parity with the reference's ``unique_integer`` / name mangling in
``python/paddle/v2/fluid/framework.py`` (``unique_name``), re-done as a plain
thread-safe counter; no C++ side needed on TPU.
"""

import threading


class _Generator:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}

    def __call__(self, prefix):
        with self._lock:
            idx = self._counters.get(prefix, 0)
            self._counters[prefix] = idx + 1
        return "%s_%d" % (prefix, idx)

    def reset(self):
        with self._lock:
            self._counters.clear()


_generator = _Generator()


def generate(prefix):
    """Return a process-unique name with ``prefix``."""
    return _generator(prefix)


def reset():
    """Reset all counters (test isolation only)."""
    _generator.reset()


import contextlib


@contextlib.contextmanager
def guard():
    """Snapshot/restore counters so a program rebuilt inside the guard gets
    the same generated names — required for checkpoint name stability when
    building a model more than once per process (fluid unique_name.guard
    parity)."""
    with _generator._lock:
        saved = dict(_generator._counters)
        _generator._counters.clear()
    try:
        yield
    finally:
        with _generator._lock:
            _generator._counters.clear()
            _generator._counters.update(saved)
