"""Op registry and execution context.

Capability parity with the reference OpRegistry/OpInfoMap
(``paddle/framework/op_registry.h:36,148``, ``op_info.h:34``), TPU-first:

* An op is ONE pure JAX function (``compute``). The same function serves as
  the runtime kernel (traced into the block's single XLA computation) and as
  build-time shape inference (run under ``jax.eval_shape``). The reference
  needed a separate InferShape pass plus per-device kernels per op
  (``operator.cc:461-533``); here XLA owns device lowering.
* Gradient ops do not need hand-written kernels: backward.py appends generic
  ``vjp_grad`` ops that reuse the forward compute via ``jax.vjp`` at trace
  time (see backward.py), mirroring GradOpDescMaker
  (``paddle/framework/grad_op_desc_maker.h``) without per-op grad code.
"""

import numpy as np

import jax
import jax.numpy as jnp

from .framework import convert_dtype

# Build-time stand-in for unknown (-1) dimensions during eval_shape.
_DIM_PLACEHOLDER = 8191

_registry = {}


class OpDef:
    def __init__(self, type, compute, infer_shape=None, needs_rng=False,
                 skip_eval_shape=False, stateful=False):
        self.type = type
        self.compute = compute
        self.custom_infer_shape = infer_shape
        self.needs_rng = needs_rng
        # Ops whose compute cannot run abstractly (e.g. host IO).
        self.skip_eval_shape = skip_eval_shape
        self.stateful = stateful


def register_op(type, compute=None, **kwargs):
    """Register an op. Usable as a decorator:  @register_op("relu")"""
    def deco(fn):
        if type in _registry:
            raise ValueError("op %r already registered" % type)
        _registry[type] = OpDef(type, fn, **kwargs)
        return fn
    if compute is not None:
        return deco(compute)
    return deco


def get_op_def(type):
    try:
        return _registry[type]
    except KeyError:
        raise NotImplementedError("no TPU op registered for type %r" % type)


def registered_ops():
    return sorted(_registry)


class ExecContext:
    """What an op's compute sees: bound input values + attrs (+ rng key).

    The analog of the reference ExecutionContext (``operator.h:177``) without
    Scope/DeviceContext — values are JAX arrays (or tracers) bound by the
    executor before the call, so compute is a pure function.
    """

    __slots__ = ("op", "_values", "rng_key", "block", "trace")

    def __init__(self, op, values, rng_key=None, block=None, trace=None):
        self.op = op
        self._values = values  # slot -> list of values (None for missing)
        self.rng_key = rng_key
        self.block = block
        self.trace = trace  # executor _TraceState (None in abstract eval)

    def input(self, slot, default=None):
        vals = self._values.get(slot)
        if not vals:
            return default
        return vals[0]

    def inputs(self, slot):
        return self._values.get(slot) or []

    def has_input(self, slot):
        vals = self._values.get(slot)
        return bool(vals) and vals[0] is not None

    def attr(self, name, default=None):
        return self.op.attrs.get(name, default)

    def output_names(self, slot):
        return self.op.outputs.get(slot, [])


def flat_input_slots(op):
    """Deterministic (slot, index) ordering of an op's inputs (for vjp)."""
    out = []
    for slot in sorted(op.inputs):
        for i in range(len(op.inputs[slot])):
            out.append((slot, i))
    return out


def flat_output_slots(op):
    out = []
    for slot in sorted(op.outputs):
        for i in range(len(op.outputs[slot])):
            out.append((slot, i))
    return out


def normalize_outputs(op, result):
    """compute() returns {slot: value-or-list}; normalize to {slot: list}."""
    norm = {}
    for slot, val in result.items():
        if isinstance(val, (list, tuple)):
            norm[slot] = list(val)
        else:
            norm[slot] = [val]
    return norm


def infer_shape(op, block):
    """Set output var shapes/dtypes by abstract-evaluating compute()."""
    opdef = get_op_def(op.type)
    if opdef.custom_infer_shape is not None:
        opdef.custom_infer_shape(op, block)
        return
    if opdef.skip_eval_shape:
        return

    # Bind abstract inputs from block metadata.
    specs = {}
    for slot, names in op.inputs.items():
        vals = []
        for name in names:
            var = block.var_or_none(name)
            if var is None or var.shape is None:
                return  # cannot infer
            shape = tuple(_DIM_PLACEHOLDER if d in (-1, None) else d
                          for d in var.shape)
            vals.append(jax.ShapeDtypeStruct(shape, convert_dtype(var.dtype)))
        specs[slot] = vals

    def abstract_fn():
        rng = jax.random.PRNGKey(0) if opdef.needs_rng else None
        ctx = ExecContext(op, specs_to_values(), rng_key=rng, block=block)
        result = normalize_outputs(op, opdef.compute(ctx))
        flat = []
        for slot, _ in _out_slots:
            vals = result.get(slot, [])
            flat.append(vals.pop(0) if vals else None)
        # eval_shape needs a pytree of arrays; None is fine (leaf dropped)
        return flat

    # We need real tracers: wrap specs via closure over eval_shape inputs.
    leaf_specs = []
    leaf_index = {}
    for slot, vals in specs.items():
        for i, s in enumerate(vals):
            leaf_index[(slot, i)] = len(leaf_specs)
            leaf_specs.append(s)

    _out_slots = flat_output_slots(op)

    _current_leaves = []

    def specs_to_values():
        values = {}
        for slot, vals in specs.items():
            values[slot] = [_current_leaves[leaf_index[(slot, i)]]
                            for i in range(len(vals))]
        return values

    def wrapped(*leaves):
        _current_leaves[:] = leaves
        return abstract_fn()

    try:
        out_structs = jax.eval_shape(wrapped, *leaf_specs)
    except Exception as e:  # surface op name for debuggability
        raise type(e)("shape inference failed for op %r: %s" % (op.type, e)) \
            from e

    for (slot, i), struct in zip(_out_slots, out_structs):
        names = op.outputs.get(slot, [])
        if i >= len(names) or struct is None:
            continue
        var = block.var_or_none(names[i])
        if var is None:
            continue
        shape = tuple(-1 if d == _DIM_PLACEHOLDER else d for d in struct.shape)
        var.shape = shape
        var.dtype = convert_dtype(struct.dtype)
