"""Scope: runtime storage for persistable variables.

Parity with the reference Scope/Variable (``paddle/framework/scope.h``), but a
Scope here only holds *persistable* state (parameters, optimizer accumulators,
RNG key, metric states) as JAX arrays. Temporaries never materialize: they are
values inside the traced XLA computation (the reference materialized every
intermediate in a per-run local scope — ``executor.cc:86-114``).
"""

import contextlib

import numpy as np


class Scope:
    def __init__(self):
        self._vars = {}

    def find_var(self, name):
        return self._vars.get(name)

    def has_var(self, name):
        return name in self._vars

    def set_var(self, name, value):
        self._vars[name] = value

    def erase(self, name):
        self._vars.pop(name, None)

    def var_names(self):
        return list(self._vars)

    def items(self):
        return self._vars.items()

    def clear(self):
        self._vars.clear()


_global_scope = Scope()


def global_scope():
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    prev, _global_scope = _global_scope, scope
    try:
        yield
    finally:
        _global_scope = prev
