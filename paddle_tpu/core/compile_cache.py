"""Persistent on-disk compile cache: restart = deserialize, not compile.

The executor's in-memory ``_CacheEntry`` table dies with the process, so
every replica start re-traces and re-compiles every bucket and every
trainer restart recompiles the step — fine for a lab, fatal for an
autoscaling fleet spinning replicas up under load. This module mirrors
that table onto disk (config flag ``compile_cache_dir``): each entry is
the ``jax.stages.Compiled`` executable serialized through
``jax.experimental.serialize_executable`` plus a per-entry JSON manifest
carrying its sha256 digest and the compile environment fingerprint.

**Key stability.** The in-memory key leads with ``program._uid`` — a
per-process counter, useless across restarts. The persistent key is a
sha256 over the *content*: the program's serialized dict
(core/serialization.py), the feed signature, fetch names, donation,
every trace-time flag that keys the in-memory cache, the ingest specs,
and the environment fingerprint (jax/jaxlib versions, backend platform,
device kind and count, XLA_FLAGS). Same source program + same shapes +
same flags + same machine shape ⇒ same digest; anything else is a clean
miss, never a wrong executable.

**Corruption tolerance** (the PR-3 checkpoint discipline): every load
digest-verifies the blob against its manifest; a truncated, bit-flipped,
or unpicklable entry — or one whose manifest is itself torn — is
quarantined to ``corrupt_*`` (bounded evidence, like checkpoint
quarantine) and the caller silently falls back to a normal compile. A
poisoned cache dir can cost a cold start its fast path, never a crash
and never a mis-executed step (the digest covers the whole blob; an
environment mismatch is a skip, not a quarantine). The chaos hook
``cache_corrupt`` (resilience/faults.py) injects exactly this failure.

Trust boundary: the serialized executable format pickles XLA-internal
objects, so (unlike the data-only ``__model__`` JSON) cache dirs and
``compiled/`` artifact members must come from a writer you trust.

**Size bound** (``compile_cache_max_bytes`` flag; 0 = unbounded):
``store()`` publishes its entry first, then evicts coldest entries —
``.bin`` and manifest together, ordered by mtime, which ``load()``
touches on every hit so the ordering is least-recently-USED — until
the dir fits. The just-published entry is never evicted (a cap
smaller than one entry must not make the cache thrash itself empty),
and eviction is store-path-only: a capped dir costs nothing on the
hit path beyond the mtime touch.

Counters (always-on; every event here is a cold-start event, never a
per-step cost): ``paddle_deploy_cache_hits_total`` /
``_misses_total`` / ``_quarantined_total`` / ``_evictions_total``.
"""

import hashlib
import json
import os
import pickle
import threading

import jax

from ..observability import metrics as _metrics
from ..utils import log as _log

__all__ = ["PersistentCompileCache", "active_cache", "entry_digest",
           "env_fingerprint", "serialize_compiled",
           "deserialize_compiled"]

CACHE_HITS = _metrics.REGISTRY.counter(
    "paddle_deploy_cache_hits_total",
    "Persistent compile-cache entries deserialized instead of compiled")
CACHE_MISSES = _metrics.REGISTRY.counter(
    "paddle_deploy_cache_misses_total",
    "Persistent compile-cache lookups that fell through to an XLA "
    "compile (absent, env-skewed, or quarantined entry)")
CACHE_QUARANTINED = _metrics.REGISTRY.counter(
    "paddle_deploy_cache_quarantined_total",
    "Persistent compile-cache entries moved to corrupt_* after failing "
    "digest verification or deserialization")
CACHE_EVICTIONS = _metrics.REGISTRY.counter(
    "paddle_deploy_cache_evictions_total",
    "Persistent compile-cache entries evicted (mtime-LRU) to keep the "
    "dir under compile_cache_max_bytes")


class _CorruptEntry(Exception):
    """Internal: entry present but failed verification/deserialization."""


def env_fingerprint():
    """Everything that silently changes what an XLA executable means:
    a serialized binary deserialized into a different environment is a
    MISS, not a candidate."""
    try:
        dev = jax.devices()[0]
        platform, kind, n = dev.platform, \
            getattr(dev, "device_kind", ""), len(jax.devices())
    except RuntimeError:  # no backend yet
        platform, kind, n = "none", "", 0
    import jaxlib
    return {
        "jax": jax.__version__,
        "jaxlib": getattr(jaxlib, "__version__", ""),
        "platform": platform,
        "device_kind": kind,
        "n_devices": n,
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
    }


def entry_digest(program, skey_parts):
    """Stable cross-process digest for one executor cache entry.

    ``skey_parts`` is the in-memory cache key minus its process-local
    head (program uid/version), recorded on the entry by
    ``Executor._prepare``; the program itself contributes through its
    serialized content, so a program rebuilt by the same user code — or
    re-read from an exported ``__model__`` — lands on the same digest.
    """
    from .serialization import program_to_dict
    doc = {
        "program": program_to_dict(program),
        "sig": repr(skey_parts),
        "env": env_fingerprint(),
    }
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()


def serialize_compiled(compiled):
    """One self-contained blob for a ``jax.stages.Compiled``: the PJRT
    executable payload plus the arg/out pytree defs (which jax's
    ``serialize`` hands back separately because pytrees aren't part of
    its payload). Raises ValueError when the backend's compilation
    doesn't support serialization."""
    from jax.experimental import serialize_executable as _se
    payload, in_tree, out_tree = _se.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree))


def deserialize_compiled(blob):
    from jax.experimental import serialize_executable as _se
    payload, in_tree, out_tree = pickle.loads(blob)
    return _se.deserialize_and_load(payload, in_tree, out_tree)


def sha256_bytes(data):
    return hashlib.sha256(data).hexdigest()


def _write_atomic(path, data, mode="wb"):
    # pid + thread id: two threads storing the same digest must not
    # interleave into one temp file (the loser's os.replace publishes
    # a whole file either way)
    tmp = "%s.tmp.%d.%d" % (path, os.getpid(), threading.get_ident())
    with open(tmp, mode) as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class PersistentCompileCache:
    """Directory of serialized executables, one ``entry_<digest>.bin``
    + ``entry_<digest>.json`` manifest per compile-cache entry."""

    def __init__(self, dirname, max_bytes=0):
        self.dirname = str(dirname)
        # 0 = unbounded; refreshed from the compile_cache_max_bytes
        # flag by active_cache() so a flag change applies to the
        # already-constructed instance
        self.max_bytes = int(max_bytes or 0)
        self._serialize_unsupported = False  # log the first failure only

    def _bin(self, digest):
        return os.path.join(self.dirname, "entry_%s.bin" % digest)

    def _meta(self, digest):
        return os.path.join(self.dirname, "entry_%s.json" % digest)

    def load(self, digest):
        """The deserialized ``Compiled`` for ``digest``, or None.

        Never raises: absent/env-skewed entries are plain misses;
        corrupt entries (torn manifest, digest mismatch, unpicklable
        blob, injected ``cache_corrupt`` fault) are quarantined and
        reported as misses — the caller recompiles."""
        bin_path, meta_path = self._bin(digest), self._meta(digest)
        if not (os.path.exists(bin_path) and os.path.exists(meta_path)):
            CACHE_MISSES.inc()
            return None
        try:
            from ..resilience import faults as _faults
            _faults.fire_point("cache_corrupt")
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
            except (OSError, ValueError) as e:
                raise _CorruptEntry("unreadable manifest: %r" % (e,))
            if meta.get("env") != env_fingerprint():
                # a different jax/backend/topology is SKEW, not damage:
                # leave the entry for the environment that wrote it
                _log.structured("compile_cache_env_skew", digest=digest,
                                entry_env=meta.get("env"))
                CACHE_MISSES.inc()
                return None
            with open(bin_path, "rb") as f:
                blob = f.read()
            if sha256_bytes(blob) != meta.get("sha256"):
                raise _CorruptEntry("blob digest mismatch")
            compiled = deserialize_compiled(blob)
        except Exception as e:
            self._quarantine(digest, repr(e))
            CACHE_MISSES.inc()
            return None
        CACHE_HITS.inc()
        try:
            # LRU touch: a hit entry must outrank write-once-read-
            # never entries when the size cap evicts by mtime
            os.utime(bin_path)
            os.utime(meta_path)
        except OSError:
            pass
        return compiled

    def store(self, digest, compiled):
        """Serialize + publish one entry (atomic per file; the manifest
        lands last, so a crashed writer leaves an entry without a
        manifest — a plain miss). Best-effort: serialization
        unsupported on this backend, or a read-only dir, just means no
        persistent cache."""
        try:
            blob = serialize_compiled(compiled)
        except Exception as e:
            if not self._serialize_unsupported:
                self._serialize_unsupported = True
                _log.structured("compile_cache_serialize_unsupported",
                                error=repr(e))
            return False
        try:
            os.makedirs(self.dirname, exist_ok=True)
            _write_atomic(self._bin(digest), blob)
            _write_atomic(
                self._meta(digest),
                json.dumps({"sha256": sha256_bytes(blob),
                            "bytes": len(blob),
                            "env": env_fingerprint()}).encode())
        except OSError as e:
            _log.structured("compile_cache_store_failed", digest=digest,
                            error=repr(e))
            return False
        self._evict_lru(keep_digest=digest)
        return True

    def _evict_lru(self, keep_digest):
        """Bound the dir to ``max_bytes``: drop whole entries (bin +
        manifest together — a half-evicted entry is just a future
        manifestless miss) coldest-mtime first until the cap fits.
        The entry just published is exempt: a cap smaller than one
        executable must degrade to "cache of one", not evict the
        thing it was asked to keep. Best-effort like store() itself —
        a concurrent writer/evictor losing a race is no error."""
        if not self.max_bytes:
            return
        try:
            entries = {}  # digest -> [mtime, bytes, paths]
            for fname in os.listdir(self.dirname):
                # skip quarantine evidence (bounded separately) and a
                # concurrent writer's in-flight temp files
                if not fname.startswith("entry_") or ".tmp." in fname:
                    continue
                digest = fname[len("entry_"):].rsplit(".", 1)[0]
                path = os.path.join(self.dirname, fname)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                ent = entries.setdefault(digest, [0.0, 0, []])
                ent[0] = max(ent[0], st.st_mtime)
                ent[1] += st.st_size
                ent[2].append(path)
            total = sum(e[1] for e in entries.values())
            for digest in sorted(entries, key=lambda d: entries[d][0]):
                if total <= self.max_bytes:
                    break
                if digest == keep_digest:
                    continue
                for path in entries[digest][2]:
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                total -= entries[digest][1]
                CACHE_EVICTIONS.inc()
                _log.structured("compile_cache_evicted", digest=digest,
                                freed_bytes=entries[digest][1])
        except OSError:
            pass

    def _quarantine(self, digest, reason):
        """Move a corrupt entry aside (evidence, like checkpoint
        quarantine) and bound the evidence to the newest few."""
        moved = False
        for path in (self._bin(digest), self._meta(digest)):
            if not os.path.exists(path):
                continue
            dst = os.path.join(self.dirname,
                               "corrupt_" + os.path.basename(path))
            n = 0
            while os.path.exists(dst):
                n += 1
                dst = os.path.join(self.dirname, "corrupt_%d_%s"
                                   % (n, os.path.basename(path)))
            try:
                os.rename(path, dst)
                moved = True
            except OSError:
                pass
        if moved:
            CACHE_QUARANTINED.inc()
            _log.structured("compile_cache_quarantined", digest=digest,
                            reason=reason)
            try:
                # bound the evidence to the newest 8 ENTRIES, pruning
                # an entry's .bin and .json together (a stem-split
                # prune would orphan a digestless blob or a blobless
                # manifest — useless as evidence either way)
                groups = {}
                for fname in os.listdir(self.dirname):
                    if not fname.startswith("corrupt_"):
                        continue
                    path = os.path.join(self.dirname, fname)
                    stem = os.path.splitext(fname)[0]
                    mtime, paths = groups.setdefault(stem, (0.0, []))
                    groups[stem] = (max(mtime, os.path.getmtime(path)),
                                    paths)
                    paths.append(path)
                for stem in sorted(groups,
                                   key=lambda s: groups[s][0])[:-8]:
                    for path in groups[stem][1]:
                        os.remove(path)
            except OSError:
                pass


_ACTIVE = {}
_ACTIVE_LOCK = threading.Lock()


def active_cache():
    """The PersistentCompileCache for the ``compile_cache_dir`` flag,
    or None when the flag is unset (zero filesystem access)."""
    from .. import config as _config
    dirname = _config.get_flag("compile_cache_dir")
    if not dirname:
        return None
    dirname = os.path.abspath(str(dirname))
    with _ACTIVE_LOCK:
        cache = _ACTIVE.get(dirname)
        if cache is None:
            cache = PersistentCompileCache(dirname)
            _ACTIVE[dirname] = cache
        # store-path-only flag (load never consults it): refresh here
        # so a flag change reaches the cached instance
        cache.max_bytes = int(
            _config.get_flag("compile_cache_max_bytes") or 0)
        return cache
