"""Program <-> JSON dict: portable, versioned, data-only serialization.

Reference analog: ``framework.proto:33-146`` — ProgramDesc as a versioned
schema that inference engines load without running code. We serialize the
IR as JSON instead of protobuf: the IR is small (op type + name lists +
attrs) and JSON keeps the ``__model__`` export human-readable and safe to
load from untrusted sources (no code execution on load, unlike pickle).

Attr values beyond JSON primitives are tagged:
* tuples          -> {"__tuple__": [...]}
* dtypes          -> {"__dtype__": "float32"}
* numpy arrays    -> {"__ndarray__": {"dtype", "shape", "data"}}
* Operator refs   -> {"__op_ref__": [block_idx, op_idx]}  (vjp_grad.fwd_op)
* nested dicts    -> {"__map__": {...}}
"""

import numpy as np

import jax.numpy as jnp

from .framework import Program, Block, Variable, Parameter, Operator

FORMAT_VERSION = 1

__all__ = ["program_to_dict", "program_from_dict", "FORMAT_VERSION"]


def _dtype_name(dt):
    if dt is jnp.bfloat16 or str(dt) == "bfloat16":
        return "bfloat16"
    return np.dtype(dt).name


def _encode_attr(value, op_index, top_level=True):
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, (np.integer,)):
        return int(value)
    if isinstance(value, (np.floating,)):
        return float(value)
    if isinstance(value, tuple):
        return {"__tuple__": [_encode_attr(v, op_index, False)
                              for v in value]}
    if isinstance(value, list):
        return [_encode_attr(v, op_index, False) for v in value]
    if isinstance(value, np.dtype) or value is jnp.bfloat16:
        return {"__dtype__": _dtype_name(value)}
    if isinstance(value, np.ndarray):
        return {"__ndarray__": {"dtype": value.dtype.name,
                                "shape": list(value.shape),
                                "data": value.ravel().tolist()}}
    if isinstance(value, Operator):
        # The patch pass rewrites op.attrs[key]; a ref buried inside a
        # tuple/list/map could not be patched in place, so refuse rather
        # than silently corrupt on load.
        if not top_level:
            raise TypeError("Operator references are only supported as "
                            "top-level attr values")
        ref = op_index.get(id(value))
        if ref is None:
            raise ValueError("attr references an Operator outside the "
                             "program being serialized")
        return {"__op_ref__": list(ref)}
    if isinstance(value, dict):
        return {"__map__": {k: _encode_attr(v, op_index, False)
                            for k, v in value.items()}}
    raise TypeError("cannot serialize op attr of type %r (value %r) — "
                    "attrs must be data, not live objects"
                    % (type(value).__name__, value))


def _decode_attr(value, pending_refs, holder, top_level=True):
    if isinstance(value, dict):
        if "__tuple__" in value:
            return tuple(_decode_attr(v, pending_refs, holder, False)
                         for v in value["__tuple__"])
        if "__dtype__" in value:
            name = value["__dtype__"]
            return jnp.bfloat16 if name == "bfloat16" else np.dtype(name)
        if "__ndarray__" in value:
            d = value["__ndarray__"]
            return np.array(d["data"], dtype=d["dtype"]).reshape(d["shape"])
        if "__op_ref__" in value:
            if not top_level:
                raise ValueError("nested Operator reference in attr — "
                                 "unsupported format")
            pending_refs.append((holder, tuple(value["__op_ref__"])))
            return None  # patched in the second pass
        if "__map__" in value:
            return {k: _decode_attr(v, pending_refs, holder, False)
                    for k, v in value["__map__"].items()}
        raise ValueError("unrecognized tagged attr: %r" % (value,))
    if isinstance(value, list):
        return [_decode_attr(v, pending_refs, holder, False)
                for v in value]
    return value


def _encode_var(v):
    return {
        "class": "Parameter" if isinstance(v, Parameter) else "Variable",
        "name": v.name,
        "shape": list(v.shape) if v.shape is not None else None,
        "dtype": _dtype_name(v.dtype),
        "persistable": bool(v.persistable),
        "stop_gradient": bool(v.stop_gradient),
        "trainable": bool(v.trainable),
        "is_data": bool(getattr(v, "is_data", False)),
    }


def program_to_dict(program):
    op_index = {}
    for b in program.blocks:
        for i, op in enumerate(b.ops):
            op_index[id(op)] = (b.idx, i)
    blocks = []
    for b in program.blocks:
        blocks.append({
            "idx": b.idx,
            "parent_idx": b.parent_idx,
            "vars": [_encode_var(v) for v in b.vars.values()],
            "ops": [{
                "type": op.type,
                "inputs": {k: list(v) for k, v in op.inputs.items()},
                "outputs": {k: list(v) for k, v in op.outputs.items()},
                "attrs": {k: _encode_attr(v, op_index)
                          for k, v in op.attrs.items()},
            } for op in b.ops],
        })
    return {"format_version": FORMAT_VERSION,
            "random_seed": program.random_seed,
            "blocks": blocks}


def program_from_dict(data):
    version = data.get("format_version")
    if version != FORMAT_VERSION:
        raise ValueError("unsupported program format version %r (this "
                         "build reads version %d)" % (version,
                                                      FORMAT_VERSION))
    program = Program()
    program.random_seed = data.get("random_seed")
    # Materialize all blocks first (ops may reference sub-blocks by idx).
    for bd in data["blocks"]:
        if bd["idx"] == 0:
            continue
        block = Block(program, bd["idx"], bd["parent_idx"])
        assert len(program.blocks) == bd["idx"], "non-contiguous block idx"
        program.blocks.append(block)
    pending_refs = []
    for bd in data["blocks"]:
        block = program.blocks[bd["idx"]]
        for vd in bd["vars"]:
            cls = Parameter if vd["class"] == "Parameter" else Variable
            kwargs = dict(name=vd["name"], shape=vd["shape"],
                          dtype=vd["dtype"], trainable=vd["trainable"])
            if cls is Variable:
                kwargs.update(persistable=vd["persistable"])
            var = cls(block, **kwargs)
            # Parameter.__init__ doesn't take these; set for both classes
            # so e.g. a frozen parameter stays frozen after a round-trip.
            var.stop_gradient = vd["stop_gradient"]
            var.persistable = vd["persistable"]
            var.is_data = vd["is_data"]
            block.vars[var.name] = var
        for od in bd["ops"]:
            op = Operator(block, od["type"], od["inputs"], od["outputs"])
            op.attrs = {k: _decode_attr(v, pending_refs, (op, k))
                        for k, v in od["attrs"].items()}
            block.ops.append(op)
            for ns in op.outputs.values():
                for n in ns:
                    v = block.var_or_none(n)
                    if v is not None and v.op is None:
                        v.op = op
    # Second pass: resolve Operator references now that all ops exist.
    for (op, attr_key), (b_idx, o_idx) in pending_refs:
        op.attrs[attr_key] = program.blocks[b_idx].ops[o_idx]
    program._bump_version()
    return program
