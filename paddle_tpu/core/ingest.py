"""Narrow-wire ingest: packed single-copy feeds + on-device widening.

The feed path's remaining cost after async double-buffering
(reader/staging.py) is *bytes on the wire and dispatches per batch*
(PROFILE.md round 5: 8.8 ms compute vs 328.9 ms H2D for a 4.8 MB f32
batch). This module owns the two levers:

* **Packing** — all feed arrays of one batch laid out into ONE
  contiguous uint8 block (64-byte-aligned slots), transferred with one
  ``jax.device_put`` instead of one per array. The block is shaped
  ``(shards, shard_nbytes)`` so a data-parallel mesh can scatter row
  ``s`` straight to device ``s`` (no replicated full-batch transfer).
  The executor unpacks *inside* the compiled step via static slices +
  ``bitcast_convert_type`` — free for XLA to fuse, and the consumed
  ingest buffer is donated so depth-2 prefetch doesn't double HBM.
* **Widening** — feeds travel in their wire dtype (uint8 images, int32
  ids) and are cast/normalized to the model dtype on device
  (``widen``), compiled into the step like amp/nonfinite_guard.

Host-side packing works with or without the native buddy arena: the
caller passes an ``alloc`` callback for arena blocks and gets a plain
numpy fallback otherwise.
"""

import collections

import numpy as np

from .framework import convert_dtype

__all__ = ["FeedSlot", "PackedBatch", "PACKED_FEED", "SparseTriple",
           "plan_layout", "pack_feed", "unpack", "widen", "canon_norm",
           "explode_sparse"]

# Reserved feed name the executor binds a PackedBatch's buffer to.
PACKED_FEED = "@PACKED_FEED@"

# Host copy / slot alignment. 64 keeps every slot base cache-line
# aligned inside the arena block (the buddy arena already aligns the
# block base) so the staging memcpys run at full host bandwidth.
_ALIGN = 64

# One packed slot, all static: name, wire dtype (str), rows per shard,
# per-sample trailing shape, byte offset/extent within one shard row.
# The tuple is the compile-cache signature — two batches with the same
# layout share one executor entry. ``kind`` is "dense" or "sparse";
# sparse slots carry a ragged (ids, offsets, values) triple in one
# byte range with ``aux = (cap, n_offsets, index_dtype)`` (cap = the
# pow-2 nnz bucket the ids/values are padded to, so distinct nnz
# counts collapse onto a bounded set of compile signatures).
FeedSlot = collections.namedtuple(
    "FeedSlot", ["name", "dtype", "rows", "sample_shape", "offset",
                 "nbytes", "kind", "aux"],
    defaults=("dense", None))

# A ragged sparse feed: CSR-style ids [nnz] / offsets [batch+1] /
# values [nnz]. As a feed-dict value under key ``name`` it packs as ONE
# slot of the single-copy wire (ids/offsets in the index wire width)
# and unpacks inside the step as the three feeds ``name``,
# ``name@offsets``, ``name@values`` — declare data vars with those
# names to consume it. This is what keeps recsys batches on the
# one-H2D-per-batch property: the [batch+1] offsets array's ragged
# leading dim used to force the whole batch onto the per-array path.
SparseTriple = collections.namedtuple(
    "SparseTriple", ["ids", "offsets", "values"])

# nnz bucket floor for sparse slots: pad to the next power of two, at
# least this, so the packed layout (= compile signature) is closed.
_SPARSE_MIN_CAP = 64


def _sparse_cap(nnz):
    cap = _SPARSE_MIN_CAP
    while cap < nnz:
        cap *= 2
    return cap


def _pad_tail(arr, cap):
    if arr.shape[0] == cap:
        return arr
    out = np.zeros((cap,) + arr.shape[1:], arr.dtype)
    out[:arr.shape[0]] = arr
    return out


def _canon_triple(v):
    """Canonicalize a SparseTriple for the wire: ids/offsets in the
    index wire width, 1-D; values in their own canon dtype."""
    ids = _canon_array(v.ids).reshape(-1)
    offs = _canon_array(v.offsets).reshape(-1)
    vals = _canon_array(v.values).reshape(-1)
    if ids.shape[0] != vals.shape[0]:
        raise ValueError("sparse triple ids/values length mismatch "
                         "(%d vs %d)" % (ids.shape[0], vals.shape[0]))
    return ids, offs, vals


def explode_sparse(feed):
    """Replace each SparseTriple value with its three named arrays
    (ids padded to the pow-2 cap, so the per-array path sees the same
    closed shape set as the packed wire). No-op passthrough for feeds
    without triples."""
    if not any(isinstance(v, SparseTriple) for v in feed.values()):
        return feed
    out = {}
    for name, v in feed.items():
        if isinstance(v, SparseTriple):
            ids, offs, vals = _canon_triple(v)
            cap = _sparse_cap(ids.shape[0])
            out[name] = _pad_tail(ids, cap)
            out[name + "@offsets"] = offs
            out[name + "@values"] = _pad_tail(vals, cap)
        else:
            out[name] = v
    return out


class PackedBatch:
    """One batch as a single (shards, shard_nbytes) uint8 buffer.

    ``buffer`` starts as host numpy (possibly an arena-backed view) and
    is replaced by the staged device array once transferred;
    ``transfer_done`` is set by the staging thread after the H2D
    completes, which is what makes recycling the arena block safe even
    though the executor donates the device buffer.
    """

    __slots__ = ("buffer", "layout", "shards", "shard_nbytes",
                 "batch_size", "transfer_done")

    def __init__(self, buffer, layout, shards, shard_nbytes, batch_size):
        self.buffer = buffer
        self.layout = layout
        self.shards = shards
        self.shard_nbytes = shard_nbytes
        self.batch_size = batch_size
        self.transfer_done = False

    def signature(self):
        """Hashable layout key for the executor compile cache."""
        return (self.layout, self.shards, self.shard_nbytes)

    @property
    def nbytes(self):
        return self.shards * self.shard_nbytes


def _canon_array(value):
    """Host-canonicalize one feed array for the wire: the no-x64 dtype
    mapping (int64 -> int32 etc., framework.convert_dtype) applied
    BEFORE transfer, so ids/labels cross at 4 bytes instead of 8."""
    arr = np.asarray(value)
    dt = convert_dtype(arr.dtype)
    if np.dtype(dt) != arr.dtype:
        arr = arr.astype(dt)
    return np.ascontiguousarray(arr)


def _align(n):
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def plan_layout(feed, shards=1):
    """(arrays, layout, shard_nbytes, batch) for a packable feed dict,
    or None when the batch can't be packed (caller falls back to the
    per-array path): empty arrays, mismatched leading dims, a batch
    the shard count doesn't divide, or a sparse triple under a
    multi-shard scatter (ragged nnz doesn't split row-wise)."""
    if not feed:
        return None
    arrays, batch = {}, None
    for name in sorted(feed):
        value = feed[name]
        if isinstance(value, SparseTriple):
            if shards != 1:
                return None
            ids, offs, vals = _canon_triple(value)
            if offs.shape[0] < 2:
                return None
            b = offs.shape[0] - 1
            if batch is None:
                batch = b
            elif b != batch:
                return None
            arrays[name] = (ids, offs, vals)
            continue
        arr = _canon_array(value)
        if arr.ndim == 0 or arr.nbytes == 0:
            return None
        if batch is None:
            batch = arr.shape[0]
        elif arr.shape[0] != batch:
            return None
        arrays[name] = arr
    if not batch or batch % shards:
        return None
    rows = batch // shards
    layout, off = [], 0
    for name, arr in arrays.items():
        if isinstance(arr, tuple):
            ids, offs, vals = arr
            cap = _sparse_cap(ids.shape[0])
            nb = (offs.nbytes + cap * ids.itemsize
                  + cap * vals.itemsize)
            layout.append(FeedSlot(
                name, np.dtype(vals.dtype).name, batch, (), off, nb,
                kind="sparse",
                aux=(cap, offs.shape[0], np.dtype(ids.dtype).name)))
            off = _align(off + nb)
            continue
        if arr.nbytes % shards:
            return None
        nb = arr.nbytes // shards
        layout.append(FeedSlot(name, np.dtype(arr.dtype).name, rows,
                               tuple(arr.shape[1:]), off, nb))
        off = _align(off + nb)
    return arrays, tuple(layout), _align(off), batch


def pack_feed(feed, shards=1, alloc=None):
    """Pack ``feed`` into one host block; returns (PackedBatch, handle)
    or None. ``alloc(nbytes) -> (uint8 view, handle) | (None, None)``
    supplies staging memory (the buddy arena); numpy otherwise."""
    plan = plan_layout(feed, shards)
    if plan is None:
        return None
    arrays, layout, shard_nbytes, batch = plan
    total = shards * shard_nbytes
    buf, handle = (None, None)
    if alloc is not None:
        buf, handle = alloc(total)
    if buf is None:
        buf, handle = np.empty(total, np.uint8), None
    buf2d = buf.reshape(shards, shard_nbytes)
    rows = batch // shards
    for slot in layout:
        arr = arrays[slot.name]
        if slot.kind == "sparse":  # shards == 1 (plan enforces)
            ids, offs, vals = arr
            cap = slot.aux[0]
            seg = buf2d[0, slot.offset:slot.offset + slot.nbytes]
            o_nb = offs.nbytes
            i_nb = cap * ids.itemsize
            seg[:o_nb].view(offs.dtype)[:] = offs
            seg[o_nb:o_nb + i_nb].view(ids.dtype)[:] = \
                _pad_tail(ids, cap)
            seg[o_nb + i_nb:o_nb + i_nb + cap * vals.itemsize] \
                .view(vals.dtype)[:] = _pad_tail(vals, cap)
            continue
        for s in range(shards):
            dst = buf2d[s, slot.offset:slot.offset + slot.nbytes] \
                .view(arr.dtype).reshape((rows,) + slot.sample_shape)
            np.copyto(dst, arr[s * rows:(s + 1) * rows])
    return PackedBatch(buf2d, layout, shards, shard_nbytes, batch), handle


def unpack(buf, layout):
    """Traceable inverse of ``pack_feed``: static slices of the
    (shards, shard_nbytes) uint8 buffer bitcast back to each feed's
    wire dtype. Under a data-parallel sharding P(data, None) every
    slice/bitcast/reshape is shard-local — GSPMD keeps the unpacked
    feeds batch-sharded with zero collectives."""
    import jax
    shards = buf.shape[0]
    out = {}

    def _cast(seg, dt):
        k = np.dtype(dt).itemsize
        if k > 1:
            return jax.lax.bitcast_convert_type(
                seg.reshape(-1, k), dt).reshape(-1)
        if np.dtype(dt) != np.uint8:
            return jax.lax.bitcast_convert_type(seg, dt)
        return seg

    for slot in layout:
        dt = convert_dtype(slot.dtype)
        seg = jax.lax.slice_in_dim(buf, slot.offset,
                                   slot.offset + slot.nbytes, axis=1)
        if slot.kind == "sparse":
            cap, n_off, idt_name = slot.aux
            idt = convert_dtype(idt_name)
            isz = np.dtype(idt).itemsize
            flat = seg.reshape(-1)  # shards == 1 on the sparse wire
            o_nb, i_nb = n_off * isz, cap * isz
            out[slot.name + "@offsets"] = _cast(flat[:o_nb], idt)
            out[slot.name] = _cast(flat[o_nb:o_nb + i_nb], idt)
            out[slot.name + "@values"] = _cast(
                flat[o_nb + i_nb:o_nb + i_nb
                     + cap * np.dtype(dt).itemsize], dt)
            continue
        k = np.dtype(dt).itemsize
        if k > 1:
            seg = jax.lax.bitcast_convert_type(
                seg.reshape(shards, slot.nbytes // k, k), dt)
        elif np.dtype(dt) != np.uint8:
            seg = jax.lax.bitcast_convert_type(seg, dt)
        out[slot.name] = seg.reshape((shards * slot.rows,)
                                     + slot.sample_shape)
    return out


def canon_norm(v):
    """Hashable form of a scale/mean/std attr for compile-cache keys."""
    if v is None:
        return None
    arr = np.asarray(v, np.float32)
    if arr.ndim == 0:
        return float(arr)
    return tuple(float(x) for x in arr.reshape(-1))


def widen(x, target_dtype, scale=None, mean=None, std=None):
    """The on-device ingest prologue for one feed: cast the wire array
    to the model dtype, then the standard normalize chain
    ``(x * scale - mean) / std`` (each stage optional). A length-C
    vector attr broadcasts over the channel axis (axis 1 of NCHW);
    scalars broadcast everywhere. Runs inside the jitted step, so XLA
    fuses it with the first consumers and the f32 batch never exists
    in host memory or on the wire."""
    import jax.numpy as jnp
    dt = convert_dtype(target_dtype)
    x = x.astype(dt)

    def _b(v):
        v = jnp.asarray(v, dt)
        if v.ndim == 1 and x.ndim >= 2 and v.shape[0] == x.shape[1]:
            return v.reshape((1, -1) + (1,) * (x.ndim - 2))
        return v

    if scale is not None:
        x = x * _b(scale)
    if mean is not None:
        x = x - _b(mean)
    if std is not None:
        x = x / _b(std)
    return x
