"""Program IR: Variable / Operator / Block / Program.

Capability parity with the reference's Fluid IR
(``python/paddle/v2/fluid/framework.py:125,350,621,789`` — Variable / Operator /
Block / Program mirroring a C++ ProgramDesc), re-designed TPU-first:

* The IR is a pure-Python description. There is no per-op C++ kernel dispatch
  (reference ``paddle/framework/executor.cc:116-129``); instead the Executor
  traces an entire Block into ONE jitted XLA computation (see executor.py).
* Shapes/dtypes are inferred at build time by running each op's JAX
  implementation under ``jax.eval_shape`` — one source of truth for both
  shape inference and compute (reference needed separate InferShape).
* LoD is gone: variable-length sequences are represented as padded arrays
  plus explicit length/segment-id companions (XLA needs static shapes); see
  paddle_tpu/ops/sequence_ops.py.
"""

import contextlib

import numpy as np

import jax.numpy as jnp

from . import unique_name

__all__ = [
    "Variable",
    "Operator",
    "Block",
    "Program",
    "Parameter",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "switch_main_program",
    "switch_startup_program",
    "convert_dtype",
]

# Reserved scope entry holding the PRNG key threaded through random ops.
RNG_STATE_VAR = "@RNG_STATE@"


def convert_dtype(dtype):
    """Normalize a user dtype (str/np/jnp) to a numpy dtype object."""
    if dtype is None:
        return np.dtype("float32")
    if isinstance(dtype, str):
        aliases = {"float": "float32", "double": "float64", "half": "float16",
                   "int": "int32", "long": "int64", "bfloat16": "bfloat16"}
        dtype = aliases.get(dtype, dtype)
    if dtype == "bfloat16" or dtype is jnp.bfloat16:
        return jnp.bfloat16  # numpy has no bf16; keep the ml_dtypes scalar type
    dt = np.dtype(dtype)
    # TPU-native dtype policy: no 64-bit fast path on TPU; mirror the
    # reference's int64 ids / float32 data as int32 / float32 unless the
    # user enables jax x64.
    import jax
    if not jax.config.jax_enable_x64:
        dt = {np.dtype("int64"): np.dtype("int32"),
              np.dtype("uint64"): np.dtype("uint32"),
              np.dtype("float64"): np.dtype("float32")}.get(dt, dt)
    return dt


class Variable:
    """A named value in a Block.

    Mirrors the reference Variable (framework.py:125): name, shape, dtype,
    persistable flag, stop_gradient. ``shape`` may contain -1 in the batch
    position at build time; the executor specializes on concrete feed shapes.
    """

    def __init__(self, block, name=None, shape=None, dtype="float32",
                 persistable=False, stop_gradient=False, trainable=False,
                 initializer=None, is_data=False):
        self.block = block
        if name is None:
            name = unique_name.generate("tmp")
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.trainable = trainable
        self.initializer = initializer
        self.is_data = is_data
        # Narrow-wire feed declaration (layers.data wire_dtype/scale/
        # mean/std): feeds arriving in ``wire_dtype`` stay narrow on the
        # wire and are widened/normalized on device by the executor's
        # ingest prologue (core/ingest.py). None = legacy feed path.
        self.wire_dtype = None
        self.ingest = None
        self.op = None  # producing operator, if any

    @property
    def program(self):
        return self.block.program

    def __repr__(self):
        return "Variable(%s, shape=%s, dtype=%s%s)" % (
            self.name, self.shape, np.dtype(self.dtype).name
            if self.dtype is not jnp.bfloat16 else "bfloat16",
            ", persistable" if self.persistable else "")

    __str__ = __repr__


class Parameter(Variable):
    """A trainable persistable Variable (reference framework.py:931)."""

    def __init__(self, block, name=None, shape=None, dtype="float32",
                 initializer=None, regularizer=None, gradient_clip=None,
                 trainable=True, learning_rate=1.0):
        super().__init__(block, name=name, shape=shape, dtype=dtype,
                         persistable=True, trainable=trainable,
                         initializer=initializer)
        self.regularizer = regularizer
        self.gradient_clip = gradient_clip
        self.optimize_attr = {"learning_rate": learning_rate}


class Operator:
    """One op in a Block: type, named input/output var lists, attrs.

    Mirrors reference Operator (framework.py:350) minus the protobuf round
    trip. inputs/outputs map slot name -> list[str] of variable names.
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})

    def input(self, slot):
        names = self.inputs.get(slot, [])
        return names[0] if names else None

    def output(self, slot):
        names = self.outputs.get(slot, [])
        return names[0] if names else None

    def input_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    def output_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def __repr__(self):
        def fmt(d):
            return ", ".join("%s=%s" % (k, v) for k, v in sorted(d.items()))
        return "{%s: (%s) -> (%s)}" % (self.type, fmt(self.inputs),
                                       fmt(self.outputs))


class Block:
    """An ordered op list plus a var symbol table (reference framework.py:621)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}
        self.ops = []

    @property
    def parent(self):
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    def create_var(self, **kwargs):
        var = Variable(self, **kwargs)
        if var.name in self.vars:
            raise ValueError("Variable %r already exists in block %d"
                             % (var.name, self.idx))
        self.vars[var.name] = var
        return var

    def create_parameter(self, **kwargs):
        # Parameters always live in the program's global (0th) block, like the
        # reference (framework.py: global_block().create_parameter).
        gblock = self.program.global_block()
        param = Parameter(gblock, **kwargs)
        if param.name in gblock.vars:
            raise ValueError("Parameter %r already exists" % param.name)
        gblock.vars[param.name] = param
        return param

    def var(self, name):
        """Look up ``name`` in this block then ancestors (scope chaining)."""
        b = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent
        raise KeyError("Variable %r not found in block %d or ancestors"
                       % (name, self.idx))

    def has_var(self, name):
        try:
            self.var(name)
            return True
        except KeyError:
            return False

    def var_or_none(self, name):
        try:
            return self.var(name)
        except KeyError:
            return None

    def append_op(self, type, inputs=None, outputs=None, attrs=None,
                  infer_shape=True):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump_version()
        if infer_shape:
            from . import registry
            registry.infer_shape(op, self)
        for ns in op.outputs.values():
            for n in ns:
                v = self.var_or_none(n)
                if v is not None and v.op is None:
                    v.op = op
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None,
                   infer_shape=True):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump_version()
        if infer_shape:
            from . import registry
            registry.infer_shape(op, self)
        return op

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def __repr__(self):
        lines = ["Block(%d):" % self.idx]
        for v in self.vars.values():
            lines.append("  " + repr(v))
        for op in self.ops:
            lines.append("  " + repr(op))
        return "\n".join(lines)


_program_uid_counter = [0]


class Program:
    """A list of Blocks; block 0 is global (reference framework.py:789)."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self._version = 0  # bumped on mutation; part of the executor jit key
        # Monotonic uid: executor cache keys use this instead of id() so a
        # GC'd Program's id being reused can never alias a stale compile.
        _program_uid_counter[0] += 1
        self._uid = _program_uid_counter[0]
        self.random_seed = None

    # -- structure -----------------------------------------------------------
    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx=None):
        if parent_idx is None:
            parent_idx = self.current_block_idx
        block = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(block)
        self.current_block_idx = block.idx
        self._bump_version()
        return block

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def _bump_version(self):
        self._version += 1

    def all_parameters(self):
        return self.global_block().all_parameters()

    def list_vars(self):
        for block in self.blocks:
            yield from block.vars.values()

    def __repr__(self):
        return "\n".join(repr(b) for b in self.blocks)


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


def switch_main_program(program):
    global _main_program
    prev, _main_program = _main_program, program
    return prev


def switch_startup_program(program):
    global _startup_program
    prev, _startup_program = _startup_program, program
    return prev


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """Route layer construction into the given programs (reference parity)."""
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)
