"""IR-level autodiff: append backward ops to a Program.

Parity with the reference's desc-level backward pass
(``paddle/framework/backward.cc:112-415`` and
``python/paddle/v2/fluid/backward.py:338`` ``append_backward``), TPU-first:

The reference requires a hand-written GradOpDescMaker + grad kernel per op.
Here backward is symbolic at the IR level (grad ops are visible, prunable,
and transpile-able like any other op) but *generic* at the kernel level: each
appended ``vjp_grad`` op references its forward op, and at trace time the
executor links the two through ``jax.vjp`` — forward residuals are shared
inside the single XLA computation, so there is no recomputation and no per-op
grad code.

Gradient accumulation (a var consumed by N ops) follows the reference's
"sum" insertion (``backward.cc: MakeOpGrad`` dedup logic): contributions get
unique names and a ``sum`` op folds them right before first use.
"""

import numpy as np

from . import registry
from .framework import Parameter
from .executor import EMPTY_VAR

GRAD_SUFFIX = "@GRAD"

__all__ = ["append_backward", "grad_var_name", "GRAD_SUFFIX"]

# Ops that never propagate gradients (metrics, IO, optimizer updates...).
NO_GRAD_OP_TYPES = {
    "sgd", "momentum", "adam", "adamax", "adagrad", "adadelta", "rmsprop",
    "decayed_adagrad", "ftrl", "proximal_gd", "proximal_adagrad",
    "accuracy", "auc", "print", "increment", "assign_value",
    "fill_constant", "gaussian_random", "uniform_random",
}


def grad_var_name(name):
    return name + GRAD_SUFFIX


def _float_like(block, name):
    var = block.var_or_none(name)
    if var is None:
        return True
    try:
        kind = np.dtype(var.dtype).kind
    except TypeError:
        return True  # bfloat16 scalar type
    return kind == "f"


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Append grad ops for ``loss``; return [(Parameter, grad Variable)].

    The backward ops land in the same block as the forward ops, so one
    Executor.run of the program performs fwd+bwd (+optimizer ops if appended)
    as one XLA computation.
    """
    block = loss.block
    program = block.program
    if block.idx != 0:
        raise NotImplementedError("append_backward on sub-blocks not yet "
                                  "supported")
    no_grad = set(no_grad_set or ())

    if parameter_list is not None:
        param_names = set(p.name if isinstance(p, Parameter) else p
                          for p in parameter_list)
    else:
        param_names = set(p.name for p in block.all_parameters()
                          if p.trainable)
    param_names -= no_grad

    # Forward pass: which var names require grad.
    req = set(param_names)
    fwd_ops = list(block.ops)
    for op in fwd_ops:
        if op.type in NO_GRAD_OP_TYPES or op.type == "vjp_grad":
            continue
        if any(n in req for n in op.input_names()):
            for n in op.output_names():
                if n == EMPTY_VAR or n in no_grad:
                    continue
                var = block.var_or_none(n)
                if var is not None and var.stop_gradient:
                    continue
                if not _float_like(block, n):
                    continue
                req.add(n)

    if loss.name not in req:
        raise ValueError(
            "loss %r does not depend on any trainable parameter" % loss.name)

    # grad bookkeeping: var name -> {"contribs": [grad names], "final": name}
    grads = {}
    _contrib_counts = {}  # survives grads.pop on non-SSA overwrites

    def add_contrib(name):
        entry = grads.setdefault(name, {"contribs": [], "final": None})
        k = _contrib_counts.get(name, 0)
        _contrib_counts[name] = k + 1
        gname = grad_var_name(name) if k == 0 else \
            "%s%s@%d" % (name, GRAD_SUFFIX, k)
        src = block.var(name)
        block.create_var(name=gname, shape=src.shape, dtype=src.dtype,
                         stop_gradient=True)
        entry["contribs"].append(gname)
        return gname

    def final_grad(name):
        entry = grads.get(name)
        if entry is None or not entry["contribs"]:
            return None
        if entry["final"] is None:
            if len(entry["contribs"]) == 1:
                entry["final"] = entry["contribs"][0]
            else:
                out = "%s%s@SUM" % (name, GRAD_SUFFIX)
                src = block.var(name)
                block.create_var(name=out, shape=src.shape, dtype=src.dtype,
                                 stop_gradient=True)
                block.append_op("sum", inputs={"X": entry["contribs"]},
                                outputs={"Out": [out]}, infer_shape=False)
                entry["final"] = out
        return entry["final"]

    # Sparse-eligible embedding tables (SelectedRows path, reference
    # selected_rows.h / SparseRowMatrix.h): a trainable table consumed by
    # exactly ONE is_sparse lookup_table gets a (rows, values) gradient
    # instead of a dense [V, D] cotangent. Tables with any other consumer
    # fall back to the dense vjp path (contributions must sum densely).
    consumers = {}
    for op in fwd_ops:
        for n in set(op.input_names()):
            consumers[n] = consumers.get(n, 0) + 1
    sparse_tables = set()
    dist_tables = set()
    for op in fwd_ops:
        if op.type == "lookup_table" and op.attrs.get("is_sparse"):
            w = op.input("W")
            if w in param_names and consumers.get(w, 0) == 1:
                sparse_tables.add(w)
        elif op.type == "lookup_table_dist":
            # distributed tables are sparse-gradient however many
            # lookups share them (per-consumer (rows, values) pairs,
            # concatenated below): the whole point is never
            # materializing a table-sized cotangent. Only a NON-lookup
            # consumer (e.g. weight tying into a matmul) forces the
            # dense vjp path — loudly, because at DistEmbedding scale
            # that cotangent is the OOM this subsystem exists to avoid.
            w = op.input("W")
            if w in param_names:
                dist_tables.add(w)
    lookup_consumers = {}
    for op in fwd_ops:
        if op.type == "lookup_table_dist":
            w = op.input("W")
            lookup_consumers[w] = lookup_consumers.get(w, 0) + 1
    for w in sorted(dist_tables):
        if lookup_consumers.get(w, 0) != consumers.get(w, 0):
            dist_tables.discard(w)
            import logging
            logging.getLogger("paddle_tpu").warning(
                "distributed embedding table %r is consumed by a "
                "non-lookup op: its gradient falls back to a DENSE "
                "[%s] cotangent — the sparse-update guarantee does "
                "not hold for this table", w,
                "x".join(str(d) for d in block.var(w).shape))
    sparse_grads = {}  # table name -> (rows var name, values var name)
    dist_grad_parts = {}  # table name -> [(rows, vals), ...] pre-concat

    # Seed: d loss / d loss = ones.
    seed = add_contrib(loss.name)
    block.append_op("fill_like", inputs={"X": [loss.name]},
                    outputs={"Out": [seed]}, attrs={"value": 1.0},
                    infer_shape=False)

    for i in range(len(fwd_ops) - 1, -1, -1):
        op = fwd_ops[i]
        if op.type in NO_GRAD_OP_TYPES or op.type == "vjp_grad":
            continue
        if op.type == "lookup_table" and op.input("W") in sparse_tables:
            g_out = final_grad(op.output("Out"))
            if g_out is None:
                continue
            w = block.var(op.input("W"))
            rows_n = "%s%s@ROWS" % (w.name, GRAD_SUFFIX)
            vals_n = "%s%s@VALUES" % (w.name, GRAD_SUFFIX)
            block.create_var(name=rows_n, dtype="int32",
                             stop_gradient=True)
            block.create_var(name=vals_n, dtype=w.dtype,
                             stop_gradient=True)
            block.append_op(
                "lookup_table_sparse_grad",
                inputs={"OutGrad": [g_out], "Ids": [op.input("Ids")]},
                outputs={"Rows": [rows_n], "Values": [vals_n]},
                attrs={"vocab_size": int(w.shape[0]),
                       "padding_idx": op.attrs.get("padding_idx")},
                infer_shape=False)
            sparse_grads[w.name] = (rows_n, vals_n)
            continue
        if op.type == "lookup_table_dist" and op.input("W") in dist_tables:
            g_out = final_grad(op.output("Out"))
            if g_out is None:
                continue
            w = block.var(op.input("W"))
            k = len(dist_grad_parts.get(w.name, ()))
            suffix = "" if k == 0 else "@%d" % k
            rows_n = "%s%s@ROWS%s" % (w.name, GRAD_SUFFIX, suffix)
            vals_n = "%s%s@VALUES%s" % (w.name, GRAD_SUFFIX, suffix)
            block.create_var(name=rows_n, dtype="int32",
                             stop_gradient=True)
            block.create_var(name=vals_n, dtype=w.dtype,
                             stop_gradient=True)
            block.append_op(
                "lookup_table_dist_grad",
                inputs={"OutGrad": [g_out], "Ids": [op.input("Ids")]},
                outputs={"Rows": [rows_n], "Values": [vals_n]},
                attrs={"vocab_size": op.attrs.get("vocab_size"),
                       "padded_vocab": int(w.shape[0]),
                       "padding_idx": op.attrs.get("padding_idx")},
                infer_shape=False)
            dist_grad_parts.setdefault(w.name, []).append(
                (rows_n, vals_n))
            continue
        out_slots = registry.flat_output_slots(op)
        in_slots = registry.flat_input_slots(op)
        if not out_slots or not in_slots:
            continue
        out_names = [op.outputs[slot][j] for slot, j in out_slots]
        if not any(n in grads and grads[n]["contribs"] for n in out_names):
            continue
        in_names = [op.inputs[slot][j] for slot, j in in_slots]
        need = []
        for n in in_names:
            var = block.var_or_none(n)
            need.append(n != EMPTY_VAR and n in req and var is not None
                        and not var.stop_gradient and _float_like(block, n))
        if not any(need):
            continue

        out_grad_names = []
        for n in out_names:
            g = final_grad(n)
            out_grad_names.append(g if g is not None else EMPTY_VAR)
        # Consume the written vars' grad state BEFORE adding input
        # contributions: an op that overwrites a var it also reads (the
        # While carry pattern — non-SSA) must not let its own input
        # contribution alias the already-consumed output gradient.
        for n in set(out_names):
            grads.pop(n, None)
        in_grad_names = []
        for n, ok in zip(in_names, need):
            in_grad_names.append(add_contrib(n) if ok else EMPTY_VAR)

        block.append_op(
            "vjp_grad",
            inputs={"OutGrads": out_grad_names},
            outputs={"InGrads": in_grad_names},
            attrs={"fwd_op": op, "fwd_op_type": op.type},
            infer_shape=False)

    # Fold per-consumer distributed sparse grads: a table shared by N
    # lookups gets its N (rows, values) pairs concatenated along the
    # nnz axis — the optimizer's merge/scatter sums duplicates, so the
    # result equals the dense sum of contributions while staying
    # O(total ids), never O(table).
    for wname, parts in dist_grad_parts.items():
        if len(parts) == 1:
            sparse_grads[wname] = parts[0]
            continue
        w = block.var(wname)
        rows_n = "%s%s@ROWS@CAT" % (wname, GRAD_SUFFIX)
        vals_n = "%s%s@VALUES@CAT" % (wname, GRAD_SUFFIX)
        block.create_var(name=rows_n, dtype="int32",
                         stop_gradient=True)
        block.create_var(name=vals_n, dtype=w.dtype,
                         stop_gradient=True)
        block.append_op("concat",
                        inputs={"X": [r for r, _ in parts]},
                        outputs={"Out": [rows_n]},
                        attrs={"axis": 0}, infer_shape=False)
        block.append_op("concat",
                        inputs={"X": [v for _, v in parts]},
                        outputs={"Out": [vals_n]},
                        attrs={"axis": 0}, infer_shape=False)
        sparse_grads[wname] = (rows_n, vals_n)

    params_and_grads = []
    for pname in sorted(param_names):
        param = block.var(pname)
        if pname in sparse_grads:
            rows_n, vals_n = sparse_grads[pname]
            gvar = block.var(vals_n)
            gvar.selected_rows = block.var(rows_n)  # SelectedRows marker
            params_and_grads.append((param, gvar))
            continue
        g = final_grad(pname)
        if g is None:
            # Unused parameter: gradient is zeros (reference raises; we keep
            # training robust and let the optimizer apply a zero update).
            g = grad_var_name(pname)
            if block.var_or_none(g) is None:
                block.create_var(name=g, shape=param.shape,
                                 dtype=param.dtype, stop_gradient=True)
                block.append_op("fill_like", inputs={"X": [pname]},
                                outputs={"Out": [g]}, attrs={"value": 0.0},
                                infer_shape=False)
        gvar = block.var(g)
        params_and_grads.append((param, gvar))
    return params_and_grads
