"""Executor: lowers a whole Block to ONE jitted XLA computation.

This is the north-star seam (BASELINE.json): the reference Executor walks a
block and dispatches a C++/CUDA kernel per op
(``paddle/framework/executor.cc:77,116-129``, ``operator.cc:461-533``); here
the block is *traced* — each op's JAX compute runs on tracers — and the whole
program becomes a single ``jax.jit`` computation that XLA fuses and schedules
for the MXU. Persistable state (parameters, optimizer accumulators, RNG key,
metric states) lives in a Scope as device arrays and is threaded through the
jitted function with buffer donation, so parameter updates are in-place in
HBM.

Differences from the reference, by design:
* No per-op device contexts / data transforms: XLA owns layout and fusion.
* Temporaries never materialize in a Scope.
* Gradients: ``vjp_grad`` ops (appended by backward.py) are linked to their
  forward op at trace time through a vjp cache — forward activations are
  shared, nothing is recomputed, and the whole fwd+bwd+update step is still
  one XLA computation.
"""

import itertools
import time

import numpy as np

import jax
import jax.numpy as jnp

from . import compile_cache as _compile_cache
from . import ingest as _ingest
from . import registry
from .framework import (Program, Variable, default_main_program,
                        convert_dtype, RNG_STATE_VAR)
from .scope import global_scope
from ..observability import metrics as _metrics
from ..observability import request_trace as _rtrace
from ..observability import tracing as _tracing

EMPTY_VAR = "@EMPTY@"

__all__ = ["Executor", "EMPTY_VAR"]

# Compile-cache + per-step cost telemetry (hooks gated by the config
# flag "telemetry"; family creation here is one-time and free).
_CACHE_HITS = _metrics.REGISTRY.counter(
    "paddle_executor_cache_hits_total",
    "Executor.run compile-cache hits")
_CACHE_MISSES = _metrics.REGISTRY.counter(
    "paddle_executor_cache_misses_total",
    "Executor.run compile-cache misses (trace + XLA compile)")
_TRACE_SECONDS = _metrics.REGISTRY.gauge(
    "paddle_executor_trace_seconds",
    "Python block trace + StableHLO lowering wall time per "
    "compile-cache key",
    labelnames=("key",))
_COMPILE_SECONDS = _metrics.REGISTRY.gauge(
    "paddle_executor_compile_seconds",
    "XLA compile wall time per compile-cache key",
    labelnames=("key",))
_STEP_FLOPS = _metrics.REGISTRY.gauge(
    "paddle_executor_step_flops",
    "XLA cost-analysis FLOPs of the cached step (MFU numerator)",
    labelnames=("key",))
_STEP_BYTES = _metrics.REGISTRY.gauge(
    "paddle_executor_step_bytes",
    "XLA cost-analysis bytes accessed of the cached step "
    "(bandwidth-roofline numerator)",
    labelnames=("key",))


# Global key_id source: labels must not alias across Executors or
# threads (itertools.count.__next__ is atomic under the GIL).
_KEY_IDS = itertools.count(1)


def _dtype_str(dt):
    return "bfloat16" if dt is jnp.bfloat16 else np.dtype(dt).name


def _ingest_spec(var, arriving_dtype, name, packed=False):
    """The prologue step (name, target_dtype, scale, mean, std) for one
    feed arriving as ``arriving_dtype``, or None when the feed needs no
    on-device work. Normalize attrs fire ONLY for wire-form arrivals:
    an already-widened (host-normalized) feed is the legacy path and
    must stay byte-identical."""
    if var is None:
        return None
    target = convert_dtype(var.dtype)
    wire = getattr(var, "wire_dtype", None)
    try:
        arriving = np.dtype(arriving_dtype)
    except TypeError:
        arriving = arriving_dtype  # bf16 scalar type
    if wire is not None and arriving == np.dtype(wire):
        norm = getattr(var, "ingest", None) or {}
        return (name, _dtype_str(target),
                _ingest.canon_norm(norm.get("scale")),
                _ingest.canon_norm(norm.get("mean")),
                _ingest.canon_norm(norm.get("std")))
    if packed and arriving != np.dtype(target):
        # packed feeds skip the host-side asarray cast, so any residual
        # dtype gap is closed on device instead
        return (name, _dtype_str(target), None, None, None)
    return None


class _CacheEntry:
    """One compile-cache slot: the jitted callable, io signature, and —
    when telemetry AOT-compiled the step, the persistent cache
    deserialized it, or a serving artifact primed it — the
    jax.stages.Compiled executable (avoids the double-compile the jit
    call path would pay after a cost-analysis compile).

    ``skey_parts`` is the in-memory cache key minus its process-local
    head (program uid/version) — the stable half of the persistent
    cache digest (core/compile_cache.py); ``pkey`` memoizes that digest
    once computed."""

    __slots__ = ("fn", "read", "written", "needs_rng", "key_id", "aot",
                 "aot_failed", "skey_parts", "pkey")

    def __init__(self, fn, read, written, needs_rng, key_id):
        self.fn = fn
        self.read = read
        self.written = written
        self.needs_rng = needs_rng
        self.key_id = key_id
        self.aot = None
        self.aot_failed = False
        self.skey_parts = None
        self.pkey = None


def _lookup(env, name, op, block):
    try:
        return env[name]
    except KeyError:
        from .enforce import EnforceNotMet
        reader = op.type if op is not None else "<fetch>"
        var = block.var_or_none(name)
        if var is not None and var.persistable:
            raise EnforceNotMet(
                "persistable variable %r read by %r is not initialized in "
                "scope — run the startup program first" % (name, reader))
        raise EnforceNotMet("%r reads undefined variable %r"
                            % (reader, name)) from None


# Mixed-precision op lists (config flag "amp"). WHITE ops are the MXU
# flop sinks: their float inputs are cast to the amp dtype *inside* the
# op's vjp-wrapped function, so the cast's transpose restores f32 param
# cotangents (master weights fall out of autodiff). BLACK ops are
# numerically sensitive reductions: float inputs are forced to f32.
# Everything else runs in whichever dtype flows in (XLA fuses the
# converts into neighbouring HLO).
AMP_WHITE = frozenset({
    "conv2d", "conv3d", "conv2d_transpose", "conv3d_transpose",
    "mul", "matmul", "bilinear_tensor_product",
    # fused recurrent scans: per-step gate matmuls dominate; the scan
    # carries stay bf16 end-to-end (cast once at the boundary)
    "dynamic_lstm", "dynamic_gru", "attention_gru_decoder",
    "sequence_conv",
})
AMP_BLACK = frozenset({
    "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "square_error_cost",
    "huber_loss", "nce", "cos_sim", "squared_l2_distance",
})


def _amp_cast(op_type, val, amp_dtype):
    dt = getattr(val, "dtype", None)
    if dt is None or not jnp.issubdtype(dt, jnp.floating):
        return val
    if op_type in AMP_WHITE and dt == jnp.float32:
        return val.astype(amp_dtype)
    if op_type in AMP_BLACK and dt != jnp.float32:
        return val.astype(jnp.float32)
    return val


class _TraceState:
    """Per-trace mutable state shared across ops in one block execution."""

    def __init__(self, needs_vjp, nan_guards=None, amp=None, quant=None):
        self.vjp_cache = {}   # id(fwd_op) -> (vjp_fn, flat_out_values)
        self.needs_vjp = needs_vjp
        self.amp = jnp.dtype(amp) if amp else None
        # When not None: the program's _quant_compute tag (serving/quant.py
        # arm/install) — {"vars": {weight_name: axis}, "pallas": bool,
        # "key": hashable}. Forward mul/matmul/conv2d consult
        # ops/quant_ops.maybe_quant_compute for the int8 path.
        self.quant = quant
        # When not None: dict collecting per-op finiteness predicates
        # ("op#i:type:var" -> scalar bool). The reference scans every op's
        # outputs under FLAGS_check_nan_inf (framework/executor.cc:120-128);
        # under jit we can't raise mid-trace, so we emit the predicates into
        # the computation and the host checks them after the step. Covers
        # the main block and static_rnn sub-blocks (AND-reduced over time,
        # see control_flow_ops); while/cond (forward-only generation paths)
        # are checked at their op outputs only.
        self.nan_guards = nan_guards


def _gather_inputs(op, env, block):
    values = {}
    for slot, names in op.inputs.items():
        values[slot] = [None if n == EMPTY_VAR else _lookup(env, n, op, block)
                        for n in names]
    return values


def _write_outputs(op, env, norm_result):
    for slot, names in op.outputs.items():
        vals = norm_result.get(slot, [])
        for i, name in enumerate(names):
            if name == EMPTY_VAR:
                continue
            if i < len(vals) and vals[i] is not None:
                env[name] = vals[i]


def _execute_forward_op(op, env, block, trace):
    opdef = registry.get_op_def(op.type)
    values = _gather_inputs(op, env, block)
    rng_key = None
    if opdef.needs_rng:
        env[RNG_STATE_VAR], rng_key = jax.random.split(env[RNG_STATE_VAR])

    amp = trace.amp

    if id(op) in trace.needs_vjp:
        in_slots = registry.flat_input_slots(op)
        out_slots = registry.flat_output_slots(op)
        flat_vals = [values[slot][i] for slot, i in in_slots]

        def f(*args):
            vals = {slot: list(lst) for slot, lst in values.items()}
            for (slot, i), a in zip(in_slots, args):
                # amp cast INSIDE the vjp: its transpose restores f32
                # cotangents for f32 params (master-weight recipe)
                vals[slot][i] = _amp_cast(op.type, a, amp) if amp else a
            ctx = registry.ExecContext(op, vals, rng_key=rng_key,
                                       block=block, trace=trace)
            result = registry.normalize_outputs(op, opdef.compute(ctx))
            return [result.get(slot, [None] * (i + 1))[i] if
                    i < len(result.get(slot, [])) else None
                    for slot, i in out_slots]

        outs_flat, vjp_fn = jax.vjp(f, *flat_vals)
        trace.vjp_cache[id(op)] = (vjp_fn, outs_flat)
        for (slot, i), val in zip(out_slots, outs_flat):
            names = op.outputs.get(slot, [])
            if i < len(names) and val is not None and names[i] != EMPTY_VAR:
                env[names[i]] = val
    else:
        if trace.quant is not None and op.type in ("mul", "matmul",
                                                   "conv2d"):
            from ..ops import quant_ops as _quant_ops
            result = _quant_ops.maybe_quant_compute(op, values, env, trace)
            if result is not None:
                _write_outputs(op, env,
                               registry.normalize_outputs(op, result))
                return
        if amp and (op.type in AMP_WHITE or op.type in AMP_BLACK):
            values = {slot: [_amp_cast(op.type, v, amp) for v in lst]
                      for slot, lst in values.items()}
        ctx = registry.ExecContext(op, values, rng_key=rng_key,
                                   block=block, trace=trace)
        result = registry.normalize_outputs(op, opdef.compute(ctx))
        _write_outputs(op, env, result)


def _is_float0(x):
    return getattr(x, "dtype", None) == jax.dtypes.float0


def _execute_vjp_grad(op, env, block, trace):
    fwd_op = op.attrs["fwd_op"]
    entry = trace.vjp_cache.get(id(fwd_op))
    if entry is None:
        raise RuntimeError(
            "vjp_grad for op %r executed before its forward op — backward "
            "ops must follow forward ops in the same block" % fwd_op.type)
    vjp_fn, outs_flat = entry
    grad_names = op.inputs.get("OutGrads", [])
    cots = []
    for val, gname in zip(outs_flat, grad_names):
        if val is None:
            cots.append(None)
        elif not jnp.issubdtype(val.dtype, jnp.inexact):
            # int/bool primal outputs (loop counters, conds, ids) take a
            # float0 cotangent per jax.vjp's calling convention
            cots.append(np.zeros(val.shape, dtype=jax.dtypes.float0))
        elif gname == EMPTY_VAR:
            cots.append(jnp.zeros_like(val))
        else:
            g = _lookup(env, gname, op, block)
            cots.append(jnp.asarray(g, dtype=val.dtype).reshape(val.shape))
    in_cots = vjp_fn(cots)
    out_names = op.outputs.get("InGrads", [])
    for cot, gname in zip(in_cots, out_names):
        if gname == EMPTY_VAR or cot is None or _is_float0(cot):
            continue
        env[gname] = cot


def run_block(block, env, trace):
    """Trace every op of ``block`` against ``env`` (name -> traced value)."""
    for i, op in enumerate(block.ops):
        if op.type == "vjp_grad":
            _execute_vjp_grad(op, env, block, trace)
        else:
            _execute_forward_op(op, env, block, trace)
        if trace.nan_guards is not None:
            for name in op.output_names():
                val = env.get(name)
                if val is not None and \
                        jnp.issubdtype(getattr(val, "dtype", None),
                                       jnp.floating):
                    key = "op#%d:%s:%s" % (i, op.type, name)
                    trace.nan_guards[key] = jnp.isfinite(val).all()


def _block_io(block):
    """Classify persistable reads/writes and rng need for a block."""
    read, written, needs_rng = set(), set(), False
    for op in block.ops:
        if op.type != "vjp_grad":
            if registry.get_op_def(op.type).needs_rng:
                needs_rng = True
        for names in op.inputs.values():
            for n in names:
                if n == EMPTY_VAR:
                    continue
                v = block.var_or_none(n)
                if v is not None and v.persistable and n not in written:
                    read.add(n)
        for names in op.outputs.values():
            for n in names:
                if n == EMPTY_VAR:
                    continue
                v = block.var_or_none(n)
                if v is not None and v.persistable:
                    written.add(n)
    return read, written, needs_rng


class Executor:
    """Runs Programs. Parity surface: ``fluid.Executor(place).run(...)``
    (reference ``python/paddle/v2/fluid/executor.py:71,126``)."""

    def __init__(self, place=None, strategy=None):
        """strategy: a parallel.DistStrategy — shards feeds/state over a
        device mesh; XLA inserts the collectives (replaces the reference's
        pserver/NCCL tier, SURVEY §5.8)."""
        self.place = place
        self.strategy = strategy
        self._cache = {}
        # Per-instance compile count (incremented only on a cache miss,
        # never on the steady-state hit path). Unlike the telemetry
        # counters this is flag-free: it is the proof surface for
        # closed-shape contracts — serving buckets and generation
        # (batch-bucket, cache-bucket) steps assert "exactly one
        # compile per shape across a multi-request run" against it.
        self._compiles = 0

    def _prepare(self, program, feed, fetch_list, scope, donate_state,
                 count_cache=True):
        """Shared run/lower prep: compile-cache lookup + state assembly.
        Returns (entry, state_rw, state_ro, feed_arrays). ``count_cache``
        is False for non-step callers (lower) so the hit/miss telemetry
        counts executed steps only."""
        if program is None:
            program = default_main_program()
        if not isinstance(program, Program):
            raise TypeError("Executor.run expects a Program, got %r"
                            % (program,))
        feed = {} if feed is None else feed
        fetch_list = fetch_list or []
        scope = scope or global_scope()
        block = program.global_block()

        fetch_names = [v.name if isinstance(v, Variable) else v
                       for v in fetch_list]

        # Normalize feeds. Three shapes of arrival:
        # * PackedBatch — the whole batch is ONE uint8 buffer; the step
        #   unpacks it (static slices + bitcasts) and the buffer is
        #   donated. Per-slot widening goes through the ingest prologue.
        # * wire-form array (dtype == the var's declared wire_dtype) —
        #   kept narrow; cast/normalize compiled into the step.
        # * anything else — legacy: host-side asarray cast to var dtype.
        ingest_specs, packed_sig = [], None
        if isinstance(feed, _ingest.PackedBatch):
            buf = feed.buffer
            if self.strategy is not None and isinstance(buf, np.ndarray):
                # unscattered host buffer under a mesh: replicate (still
                # one transfer per device; semantically the same global
                # batch). Staging normally pre-scatters per shard.
                buf = jax.device_put(buf, self.strategy.replicated())
            for slot in feed.layout:
                if slot.kind != "dense":
                    # sparse triples arrive in their final wire dtypes
                    # (index width ids/offsets, canon values) — no
                    # widen prologue
                    continue
                spec = _ingest_spec(block.var_or_none(slot.name),
                                    slot.dtype, slot.name, packed=True)
                if spec is not None:
                    ingest_specs.append(spec)
            packed_sig = feed.signature()
            feed_arrays = {_ingest.PACKED_FEED: buf}
            feed_sig = (("@packed@",) + packed_sig,)
        else:
            feed_arrays = {}
            feed = _ingest.explode_sparse(feed)
            for name, value in feed.items():
                var = block.var_or_none(name)
                spec = _ingest_spec(var, getattr(value, "dtype",
                                                 np.asarray(value).dtype),
                                    name)
                if spec is not None:
                    ingest_specs.append(spec)
                    arr = jnp.asarray(value)  # stays in wire dtype
                else:
                    dtype = convert_dtype(var.dtype) if var is not None \
                        else None
                    arr = jnp.asarray(value, dtype=dtype)
                feed_arrays[name] = arr
            feed_sig = tuple(sorted((n, tuple(a.shape), str(a.dtype))
                                    for n, a in feed_arrays.items()))
        ingest_specs = tuple(sorted(ingest_specs))

        from .. import config as _config
        check_nan_inf = bool(_config.get_flag("check_nan_inf"))
        nonfinite_guard = bool(_config.get_flag("nonfinite_guard"))
        amp = _config.get_flag("amp")
        flash = bool(_config.get_flag("flash_attention"))
        precision = _config.get_flag("matmul_precision")
        telemetry = bool(_config.get_flag("telemetry"))
        # distributed-embedding flags are trace-time too (layout,
        # a2a route, telemetry callbacks) but are consulted ONLY for
        # programs that registered a DistEmbedding table — the default
        # path pays one getattr, zero flag reads
        emb_tables = getattr(program, "_dist_embeddings", None)
        emb_key = None
        if emb_tables:
            emb_key = (bool(_config.get_flag("embedding_shard_rows")),
                       bool(_config.get_flag("embedding_a2a")),
                       telemetry,
                       _config.get_flag("embedding_wire_dtype"))
        # int8 quantized compute: armed programs carry their tag
        # (serving/quant.py); the default path pays one getattr, zero
        # flag reads
        quant = getattr(program, "_quant_compute", None)
        q_key = quant["key"] if quant else None
        # every trace-time flag must key the compile cache; the ingest
        # prologue (wire widening + packed unpack) is trace-time too
        key = (program._uid, program._version, feed_sig, tuple(fetch_names),
               bool(donate_state),
               self.strategy._uid if self.strategy is not None else None,
               check_nan_inf, amp, flash, precision, nonfinite_guard,
               ingest_specs, emb_key, q_key)
        entry = self._cache.get(key)
        if entry is None:
            self._compiles += 1
            if telemetry and count_cache:
                _CACHE_MISSES.inc()
            built = self._build(program, block, feed_sig, fetch_names,
                                donate_state, check_nan_inf, amp,
                                nonfinite_guard, ingest_specs, packed_sig,
                                quant)
            entry = _CacheEntry(*built, key_id="k%d" % next(_KEY_IDS))
            # the process-stable half of the persistent-cache digest
            # (key[2:] drops program uid/version, which the program's
            # serialized content replaces)
            entry.skey_parts = key[2:]
            self._cache[key] = entry
        elif telemetry and count_cache:
            _CACHE_HITS.inc()
        if entry.pkey is None and self.strategy is None and \
                _config.get_flag("compile_cache_dir"):
            # once per entry, only with the persistent cache armed:
            # hash the program content + stable key into the on-disk key
            entry.pkey = _compile_cache.entry_digest(program,
                                                     entry.skey_parts)

        state_rw, state_ro = {}, {}
        for n in entry.written:
            if scope.has_var(n):
                state_rw[n] = scope.find_var(n)
        for n in entry.read:
            if n in state_rw:
                continue
            if scope.has_var(n):
                state_ro[n] = scope.find_var(n)
            # else: executor raises at trace time with a clear message
        if entry.needs_rng:
            if not scope.has_var(RNG_STATE_VAR):
                seed = program.random_seed if program.random_seed else 0
                scope.set_var(RNG_STATE_VAR, jax.random.PRNGKey(seed))
            state_rw[RNG_STATE_VAR] = scope.find_var(RNG_STATE_VAR)

        if self.strategy is not None:
            # Scatter feeds over the mesh batch axis; pin state to its
            # PartitionSpec (no-op when already placed). GSPMD propagates
            # shardings through the step and inserts ICI collectives.
            # A packed buffer is already placed (scattered per shard by
            # the staging thread, or replicated above) — leave it be.
            feed_arrays = {n: a if n == _ingest.PACKED_FEED
                           else self.strategy.shard_feed(n, a)
                           for n, a in feed_arrays.items()}
            dist_rows = None
            if emb_key is not None and emb_key[0]:
                dist_rows = {n: info["padded"]
                             for n, info in emb_tables.items()}
            state_rw = {n: self.strategy.shard_state(n, a, dist_rows)
                        for n, a in state_rw.items()}
            state_ro = {n: self.strategy.shard_state(n, a, dist_rows)
                        for n, a in state_ro.items()}
        return entry, state_rw, state_ro, feed_arrays

    def compile_stats(self):
        """Flag-free per-executor compile counters: ``entries`` (live
        compile-cache slots) and ``compiles`` (total trace+compile
        events this executor ever paid, lower() included). A closed
        shape set shows here as a plateau: N distinct
        (program, feed-signature, flags) shapes -> exactly N compiles
        no matter how many steps run — the generation acceptance
        criterion (one compile per (batch-bucket, cache-bucket)) and
        the serving-bucket contract are asserted against this."""
        return {"entries": len(self._cache), "compiles": self._compiles}

    def lower(self, program=None, feed=None, fetch_list=None, scope=None,
              donate_state=True):
        """AOT-lower the EXACT computation ``run`` would execute (same
        donation, amp policy, state threading) without running it.
        Returns the ``jax.stages.Lowered`` — ``.compile()`` then
        ``.cost_analysis()`` / ``.as_text()`` for profiling and
        compile-checks of the true step module."""
        entry, state_rw, state_ro, feed_arrays = self._prepare(
            program, feed, fetch_list, scope, donate_state,
            count_cache=False)
        return entry.fn.lower(state_rw, state_ro, feed_arrays)

    def cache_digest(self, program, feed=None, fetch_list=None, scope=None,
                     donate_state=True):
        """The process-stable persistent-cache digest of the EXACT
        computation ``run`` would execute for these arguments (program
        content + feed/fetch signature + trace-time flags + environment
        fingerprint — core/compile_cache.py). The digest is what an AOT
        serving artifact records per bucket, so a loader can prove
        "this serialized executable IS the computation I would compile
        here" before trusting it."""
        entry, _, _, _ = self._prepare(program, feed, fetch_list, scope,
                                       donate_state, count_cache=False)
        if entry.pkey is None:
            entry.pkey = _compile_cache.entry_digest(program,
                                                     entry.skey_parts)
        return entry.pkey

    def prime_aot(self, program, feed, fetch_list, scope, compiled,
                  expect_digest=None, donate_state=True):
        """Install a deserialized ``jax.stages.Compiled`` as the AOT
        executable for the cache entry these arguments resolve to —
        the serving cold-start path: deserialize, don't compile.

        When ``expect_digest`` is given it must equal this entry's
        :meth:`cache_digest` (raises ValueError otherwise) — version
        skew, flag drift, or a different topology therefore can't
        install an executable that computes something else; callers
        catch and fall back to the compile path. If the executable
        turns out aval-incompatible anyway, ``run``'s existing AOT
        fallback degrades to the jitted path at first call."""
        entry, _, _, _ = self._prepare(program, feed, fetch_list, scope,
                                       donate_state, count_cache=False)
        if expect_digest is not None:
            if entry.pkey is None:
                entry.pkey = _compile_cache.entry_digest(
                    program, entry.skey_parts)
            if entry.pkey != expect_digest:
                raise ValueError(
                    "AOT executable digest %s does not match this "
                    "executor's computation digest %s (program/flag/"
                    "environment skew)" % (expect_digest[:12],
                                           entry.pkey[:12]))
        entry.aot = compiled
        entry.aot_failed = False
        return entry

    def _aot_compile(self, entry, state_rw, state_ro, feed_arrays):
        """Telemetry path for a compile-cache miss: AOT-compile the step
        (the jit call path would compile the same module again — the AOT
        executable is kept and used for every subsequent run), record
        per-key trace and compile wall time plus the XLA cost analysis
        (FLOPs / bytes accessed — the MFU and bandwidth-roofline
        numerators, cf. tools/mfu_probe.py)."""
        t0 = time.perf_counter()
        with _tracing.span("executorTrace", key=entry.key_id):
            lowered = entry.fn.lower(state_rw, state_ro, feed_arrays)
        t1 = time.perf_counter()
        _TRACE_SECONDS.labels(key=entry.key_id).set(t1 - t0)
        with _tracing.span("executorCompile", key=entry.key_id):
            compiled = lowered.compile()
        _COMPILE_SECONDS.labels(key=entry.key_id).set(
            time.perf_counter() - t1)
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0] if ca else {}
            _STEP_FLOPS.labels(key=entry.key_id).set(
                float(ca.get("flops", 0.0)))
            _STEP_BYTES.labels(key=entry.key_id).set(
                float(ca.get("bytes accessed", 0.0)))
        except Exception:
            pass  # cost analysis is best-effort (backend-dependent)
        entry.aot = compiled

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, donate_state=True):
        if scope is None:
            scope = global_scope()
        # request-scoped tracing: a serving layer above may have
        # activated a request's TraceContext on this thread — the
        # device call then lands as a span on that request's trace.
        # One thread-local read; no config flag, no cost when off.
        _rt_ctx = _rtrace.current()
        _rt_t0 = time.perf_counter() if _rt_ctx is not None else 0.0
        entry, state_rw, state_ro, feed_arrays = self._prepare(
            program, feed, fetch_list, scope, donate_state)
        from .. import config as _config
        if entry.aot is None and not entry.aot_failed and \
                self.strategy is None and \
                (entry.pkey is not None or _config.get_flag("telemetry")):
            # entry.pkey doubles as the "persistent cache armed" gate
            # (set in _prepare only when compile_cache_dir is on), so
            # the all-defaults path pays exactly the one telemetry
            # flag check it always did — no active_cache() call.
            pcache = _compile_cache.active_cache() \
                if entry.pkey is not None else None
            if pcache is not None:
                # restart fast path: deserialize the executable a past
                # process compiled for this exact digest. load() never
                # raises — a corrupt entry is quarantined and reported
                # as a miss, and we fall through to a normal compile.
                entry.aot = pcache.load(entry.pkey)
            if entry.aot is None and \
                    (pcache is not None or _config.get_flag("telemetry")):
                # telemetry on (cost-analysis compile, reused for
                # execution) or persistent cache armed (compile once,
                # publish for the next process): AOT-compile the step
                # so the executed step and the artifact share ONE XLA
                # compilation
                try:
                    self._aot_compile(entry, state_rw, state_ro,
                                      feed_arrays)
                except Exception:
                    entry.aot = None
                    entry.aot_failed = True  # jit call path from here on
                else:
                    if pcache is not None and entry.pkey is not None:
                        pcache.store(entry.pkey, entry.aot)
        if entry.aot is not None:
            try:
                new_state, fetches, guards = entry.aot(
                    state_rw, state_ro, feed_arrays)
            except (TypeError, ValueError):
                # aval drift vs the AOT signature (e.g. a scope var was
                # replaced with a new shape): jit retraces, AOT can't —
                # and would flap if recompiled, so stay on jit for good
                entry.aot = None
                entry.aot_failed = True
                new_state, fetches, guards = entry.fn(
                    state_rw, state_ro, feed_arrays)
        else:
            new_state, fetches, guards = entry.fn(
                state_rw, state_ro, feed_arrays)
        for n, v in new_state.items():
            scope.set_var(n, v)
        if return_numpy:
            fetches = [np.asarray(v) for v in fetches]
        if guards:
            # Per-op output scan (reference framework/executor.cc:120-128).
            bad = [k for k, ok in guards.items() if not bool(ok)]
            if bad:
                raise FloatingPointError(
                    "NaN/Inf detected in op outputs: %s" % ", ".join(bad))
        if _rt_ctx is not None:
            _rtrace.event(
                _rt_ctx, "deviceCall", key=entry.key_id,
                dur_ms=(time.perf_counter() - _rt_t0) * 1e3)
        return fetches

    def as_jax_function(self, program, feed_templates, fetch_list,
                        scope=None):
        """Export a Program block as a pure JAX function.

        Returns ``(fn, (state, feed))`` where ``fn(state, feed) -> fetches``
        is jittable and ``state`` is the persistable-variable dict read from
        ``scope`` (run the startup program first). Feeds/fetches as in
        ``run``. This is the seam for embedding programs in external JAX
        code (jit/grad/shard_map) and for AOT compile checks.
        """
        scope = scope or global_scope()
        block = program.global_block()
        fetch_names = [v.name if isinstance(v, Variable) else v
                       for v in fetch_list]
        feed = {}
        for name, value in feed_templates.items():
            var = block.var_or_none(name)
            dtype = convert_dtype(var.dtype) if var is not None else None
            feed[name] = jnp.asarray(value, dtype=dtype)
        read, written, needs_rng = _block_io(block)
        needs_vjp = {id(op.attrs["fwd_op"]) for op in block.ops
                     if op.type == "vjp_grad"}
        state = {}
        for n in sorted(read | written):
            if scope.has_var(n):
                state[n] = scope.find_var(n)
        if needs_rng:
            seed = program.random_seed if program.random_seed else 0
            state[RNG_STATE_VAR] = scope.find_var(RNG_STATE_VAR) \
                if scope.has_var(RNG_STATE_VAR) else jax.random.PRNGKey(seed)

        from .. import config as _config
        precision = _config.resolve_matmul_precision()
        amp = _config.get_flag("amp")

        def fn(state, feed):
            env = dict(state)
            env.update(feed)
            trace = _TraceState(needs_vjp, amp=amp)
            if precision is not None:
                with jax.default_matmul_precision(precision):
                    run_block(block, env, trace)
            else:
                run_block(block, env, trace)
            return [_lookup(env, n, None, block) for n in fetch_names]

        return fn, (state, feed)

    def _build(self, program, block, feed_sig, fetch_names, donate_state,
               check_nan_inf=False, amp=None, nonfinite_guard=False,
               ingest_specs=(), packed_sig=None, quant=None):
        read, written, needs_rng = _block_io(block)
        if needs_rng:
            written.add(RNG_STATE_VAR)
        if quant:
            # the per-channel scale sidecars live in the scope but are
            # not block vars, so _block_io can't see them — thread them
            # into the read set so state assembly ships them to the trace
            from ..ops import quant_ops as _quant_ops
            for _qn in quant["vars"]:
                read.add(_quant_ops.scale_var_name(_qn))
        needs_vjp = {id(op.attrs["fwd_op"]) for op in block.ops
                     if op.type == "vjp_grad"}
        written_t = tuple(sorted(written))
        read_t = tuple(sorted(read - written))

        from .. import config as _config
        from .. import parallel as _parallel
        precision = _config.resolve_matmul_precision()
        strategy = self.strategy

        packed_layout = packed_sig[0] if packed_sig is not None else None

        def fn(state_rw, state_ro, feed):
            # Ingest prologue: unpack the single-copy buffer (static
            # slices + bitcasts) and widen/normalize wire-dtype feeds to
            # their model dtype — all inside the compiled step, so the
            # wide batch exists only in HBM and XLA fuses the casts into
            # the first consumers.
            if packed_layout is not None:
                feed = _ingest.unpack(feed[_ingest.PACKED_FEED],
                                      packed_layout)
            if ingest_specs:
                feed = dict(feed)
                for name, tgt, scale, mean, std in ingest_specs:
                    feed[name] = _ingest.widen(feed[name], tgt,
                                               scale, mean, std)
            env = {}
            env.update(state_ro)
            env.update(state_rw)
            env.update(feed)
            trace = _TraceState(needs_vjp,
                                nan_guards={} if check_nan_inf else None,
                                amp=amp, quant=quant)
            prev = _parallel.set_current_strategy(strategy)
            try:
                if precision is not None:
                    with jax.default_matmul_precision(precision):
                        run_block(block, env, trace)
                else:
                    run_block(block, env, trace)
            finally:
                _parallel.set_current_strategy(prev)
            new_state = {n: env[n] for n in written_t if n in env}
            fetches = [_lookup(env, n, None, block) for n in fetch_names]
            if nonfinite_guard:
                # Guarded donated update (resilience/supervisor.py): if
                # any inexact fetch is non-finite the whole state update
                # becomes identity — a poisoned batch cannot corrupt
                # donated params/optimizer state. RNG is exempt so a
                # retried batch draws fresh randomness.
                ok = jnp.asarray(True)
                for v in fetches:
                    v = jnp.asarray(v)
                    if jnp.issubdtype(v.dtype, jnp.inexact):
                        ok = jnp.logical_and(ok, jnp.isfinite(v).all())
                new_state = {
                    n: (v if n == RNG_STATE_VAR or n not in state_rw
                        else jnp.where(ok, v, state_rw[n]))
                    for n, v in new_state.items()}
            return new_state, fetches, trace.nan_guards or {}

        # Donation: state updates are always in-place (argnum 0); a
        # packed ingest buffer (argnum 2) is consumed by exactly one
        # step, so donating it lets XLA reuse its HBM for the widened
        # batch — depth-2 prefetch without doubling ingest memory.
        donate = []
        if donate_state:
            donate.append(0)
        if packed_sig is not None:
            donate.append(2)
        jit_kwargs = {"donate_argnums": tuple(donate)} if donate else {}
        return (jax.jit(fn, **jit_kwargs), read_t, written_t, needs_rng)
