"""Structured error discipline — the enforce layer.

Parity with reference ``paddle/platform/enforce.h`` (PADDLE_ENFORCE*,
``EnforceNotMet`` carrying message + call-site) and
``paddle/utils/Error.h``: a single exception type the framework raises
for contract violations, carrying the formatted message and the
caller's file:line so failures inside a traced/jitted step still name
the op and variable that broke.
"""

import inspect

__all__ = ["EnforceNotMet", "enforce", "enforce_eq", "enforce_gt",
           "enforce_not_none"]


class EnforceNotMet(RuntimeError):
    """Reference EnforceNotMet (enforce.h:55): message + call site."""

    def __init__(self, message, site=None):
        self.site = site
        super().__init__("%s (at %s)" % (message, site)
                         if site else message)


def _site(depth=2):
    fr = inspect.stack()[depth]
    return "%s:%d" % (fr.filename.rsplit("/", 1)[-1], fr.lineno)


def enforce(cond, fmt="enforce failed", *args):
    if not cond:
        raise EnforceNotMet(fmt % args if args else fmt, _site())


def enforce_eq(a, b, fmt=None, *args):
    if a != b:
        msg = "expected %r == %r" % (a, b) if fmt is None else \
            (fmt % args if args else fmt)
        raise EnforceNotMet(msg, _site())


def enforce_gt(a, b, fmt=None, *args):
    if not a > b:
        msg = "expected %r > %r" % (a, b) if fmt is None else \
            (fmt % args if args else fmt)
        raise EnforceNotMet(msg, _site())


def enforce_not_none(v, fmt="unexpected None", *args):
    if v is None:
        raise EnforceNotMet(fmt % args if args else fmt, _site())
    return v
